"""BatchingFrontend — the request-side batcher over a ServingServer.

The reference serves "heavy traffic from millions of users" by batching
request streams into the predictor's fixed batch shape (the inference
engine scores per-batch; PAPER.md's minutes-fresh models meet
milliseconds-level scoring). Here: callers :meth:`submit` single examples
and get a Future; a dispatcher thread coalesces up to ``max_batch``
requests (or whatever arrived within ``max_wait_s``), pads to the ONE
compiled batch shape — a varying batch size would recompile the jitted
forward mid-traffic — scores once, and scatters results.

Latency accounting is the product: per-request wall time (submit →
result) lands in a TIME-WINDOWED reservoir (``serving/obs.py`` —
ISSUE 19: a since-start blend hides a swap-induced p99 step behind
hours of pre-swap samples); :meth:`stats` reports recent-traffic
p50/p99/max, batch-size distribution, and failures — the numbers
bench.py's ``serving_drill`` records and the BENCH_BEST gate holds.
``flags.serving_trace_sample`` opens a ``serve/wait`` span around every
Nth batch's coalesce window, splitting queue wait from score time in
the merged world trace.
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags
from paddlebox_tpu.monitor import context as mon_ctx
from paddlebox_tpu.serving.obs import LatencyWindow


class _Request:
    __slots__ = ("ids", "mask", "dense", "future", "t0")

    def __init__(self, ids, mask, dense):
        self.ids = ids
        self.mask = mask
        self.dense = dense
        self.future: Future = Future()
        self.t0 = time.perf_counter()


class BatchingFrontend:
    def __init__(self, server, *, max_batch: int = 256,
                 max_wait_s: float = 0.002, max_latencies: int = 100_000,
                 window_s: float | None = None):
        self.server = server
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self._q: queue.Queue[_Request | None] = queue.Queue()
        # windowed, not since-start: stats()/flight records must report
        # RECENT traffic (flags.serving_window_s; a 0 record cadence
        # still wants a sane stats window)
        self._lat = LatencyWindow(
            float(flags.serving_window_s or 30.0)
            if window_s is None else float(window_s),
            cap=int(max_latencies))
        self._lat_lock = threading.Lock()
        self._gathers = 0
        self._batches = 0
        self._batched_reqs = 0
        self._failures = 0
        self._inflight = 0                 # submitted, not yet resolved
        self._inflight_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stopping = False

    # ---- client side -----------------------------------------------------

    def submit(self, ids: np.ndarray, mask: np.ndarray,
               dense: np.ndarray | None = None) -> Future:
        """One example: ids uint64 (T,), mask bool (T,), dense f32 (F,).
        Resolves to the example's probability (scalar, or (tasks,) for
        multi-task models)."""
        if self._thread is None:
            raise RuntimeError("frontend not started (call start())")
        r = _Request(np.asarray(ids), np.asarray(mask, bool),
                     None if dense is None else np.asarray(dense,
                                                           np.float32))
        # inflight accounting rides the future's done-callback (fires
        # exactly once however the future resolves — result, exception,
        # or the stop()-drain failsafe), so the router's least-loaded
        # signal can never leak on a failure path. Registered BEFORE the
        # put: dispatch may resolve the future first.
        with self._inflight_lock:
            self._inflight += 1
        r.future.add_done_callback(self._dec_inflight)
        self._q.put(r)
        # stop() may have drained the queue between the thread check and
        # the put — a request landing in a dead queue would leave the
        # caller blocked on a forever-pending future
        if self._stopping:
            try:
                r.future.set_exception(
                    RuntimeError("frontend stopped before dispatch"))
            # pblint: disable=silent-except -- lost the resolve race:
            # drain/dispatch already set this future, which is the
            # outcome this failsafe exists to guarantee
            except Exception:   # noqa: BLE001
                pass
        return r.future

    def score(self, ids, mask, dense=None, timeout: float = 30.0):
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(ids, mask, dense).result(timeout=timeout)

    def _dec_inflight(self, _f) -> None:
        with self._inflight_lock:
            self._inflight -= 1

    @property
    def inflight(self) -> int:
        """Requests submitted but not yet resolved — the load signal the
        fleet router's two-choice least-loaded dispatch compares."""
        return self._inflight

    # ---- dispatcher ------------------------------------------------------

    def start(self) -> "BatchingFrontend":
        if self._thread is not None:
            return self
        self._stopping = False
        self._thread = mon_ctx.spawn(self._run, name="serving-frontend")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stopping = True
        self._q.put(None)              # wake the dispatcher
        self._thread.join(timeout=30)
        self._thread = None
        # fail whatever is still queued — a stopped frontend must not
        # leave callers blocked on forever-pending futures
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                break
            if r is not None and not r.future.done():
                try:
                    r.future.set_exception(
                        RuntimeError("frontend stopped before dispatch"))
                # pblint: disable=silent-except -- lost the resolve race:
                # submit()'s failsafe already set this future; either
                # way the caller is unblocked
                except Exception:   # noqa: BLE001
                    pass

    def _gather(self) -> list[_Request]:
        """Block for the first request, then coalesce until max_batch or
        the max_wait deadline."""
        first = self._q.get()
        if first is None:
            return []
        # sampled request tracing: every Nth batch's coalesce window is
        # a serve/wait span — the queue-wait half of request latency
        # (serve/score is the server's half). 0 = one flag check.
        self._gathers += 1
        n = int(flags.serving_trace_sample)
        ctx = (monitor.span("serve/wait", max_batch=self.max_batch)
               if n > 0 and self._gathers % n == 0
               else contextlib.nullcontext())
        with ctx:
            batch = [first]
            deadline = time.perf_counter() + self.max_wait_s
            while len(batch) < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    r = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if r is None:
                    break
                batch.append(r)
        return batch

    def _run(self) -> None:
        while not self._stopping:
            batch = self._gather()
            if not batch:
                continue
            # dense presence changes the predict signature — a mixed
            # batch would silently drop one side's features (or crash the
            # stack); dispatch each homogeneous group on its own
            with_dense = [r for r in batch if r.dense is not None]
            without = [r for r in batch if r.dense is None]
            for group in (with_dense, without):
                if group:
                    self._dispatch(group)

    def _dispatch(self, batch: list[_Request]) -> None:
        # claim each future before scoring (executor-style): a fleet
        # router's hedge loser cancelled while still QUEUED here is a
        # PENDING future whose cancel() succeeded — fulfilling it would
        # raise InvalidStateError out of the dispatch thread. Claiming
        # drops it from the batch and makes any later cancel() a no-op.
        batch = [r for r in batch
                 if r.future.set_running_or_notify_cancel()]
        if not batch:
            return
        n = len(batch)
        try:
            ids = np.stack([r.ids for r in batch])
            mask = np.stack([r.mask for r in batch])
            dense = (np.stack([r.dense for r in batch])
                     if batch[0].dense is not None else None)
            if n < self.max_batch:
                # pad to the ONE compiled shape (zero ids + all-false
                # mask rows pull zeros; their scores are sliced off)
                pad = self.max_batch - n
                ids = np.concatenate(
                    [ids, np.zeros((pad, ids.shape[1]), ids.dtype)])
                mask = np.concatenate(
                    [mask, np.zeros((pad, mask.shape[1]), bool)])
                if dense is not None:
                    dense = np.concatenate(
                        [dense, np.zeros((pad, dense.shape[1]),
                                         np.float32)])
            out = self.server.predict(ids, mask, dense)[:n]
        except Exception as e:   # noqa: BLE001 — fail the batch, not the loop
            self._failures += n
            monitor.counter_add("serving.frontend_failures", n)
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        now = time.perf_counter()
        wall = time.time()
        lats = [(now - r.t0) * 1e3 for r in batch]
        with self._lat_lock:
            for ms in lats:
                self._lat.add(ms, now=wall)
        self._batches += 1
        self._batched_reqs += n
        monitor.counter_add("serving.frontend_requests", n)
        for i, r in enumerate(batch):
            r.future.set_result(out[i])

    # ---- accounting ------------------------------------------------------

    def stats(self) -> dict:
        """count/failures are cumulative; the percentiles are over the
        latency WINDOW (recent traffic only — an empty window after an
        idle spell reports count with no percentiles)."""
        with self._lat_lock:
            snap = self._lat.snapshot()
        if not snap["count"]:
            return {"count": 0, "failures": self._failures}
        return {
            "count": int(self._batched_reqs),
            "failures": int(self._failures),
            "batches": int(self._batches),
            "mean_batch": round(self._batched_reqs
                                / max(self._batches, 1), 2),
            "window_count": int(snap["count"]),
            "p50_ms": round(snap["p50_ms"], 3),
            "p99_ms": round(snap["p99_ms"], 3),
            "max_ms": round(snap["max_ms"], 3),
        }
