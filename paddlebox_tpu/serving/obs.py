"""Serving-side observability (ISSUE 19): windowed latency reservoirs,
per-version score/AUC attribution, and the serving flight record.

The training plane has had the three-layer stack since PR 4/11/15: hub
records -> flight records -> doctor rules -> world trace. This module is
the serving half of that stack — the paper's "AUC runner" A/B story
needs per-version attribution ON the serving path, not just offline:

- :class:`LatencyWindow` — a time-windowed latency reservoir (the fix
  for the frontend's since-process-start blend: a swap-induced p99 step
  is visible only if old samples age out).
- :class:`VersionStats` — one served version's window: request count,
  latency window, score histogram (for the candidate-vs-stable KL), and
  a bounded pending-score FIFO that joins delayed labels back to the
  scores that version produced (the metric registry computes AUC).
- :class:`ServingObs` — the per-window bookkeeping the server drives:
  ``record()`` per scored batch, ``observe_labels()`` when delayed
  labels arrive, ``due()``/``commit()`` on the window cadence. A commit
  returns the ``serving_window`` record's fields — schema-checked by
  ``monitor/flight.validate_serving_record`` — which the server emits
  into the hub (``type="serving_record"``), aggregate merges into the
  world view, and three doctor rules read (version-regression,
  p99-burn, swap-regression).

No thread of its own: everything runs inside the server's request /
poll threads under one lock in the callers.
"""

from __future__ import annotations

import collections
import time

import numpy as np

from paddlebox_tpu.config import flags
from paddlebox_tpu.metrics.metric import MetricRegistry

# score-histogram geometry for the candidate-vs-stable divergence: 20
# equal buckets over [0, 1) plus the epsilon that keeps KL finite when
# a bucket is empty on one side
SCORE_BUCKETS = 20
_KL_EPS = 1e-6

# bounded pending-score FIFO per version: delayed labels later than
# this many batches behind are dropped (and counted) — serving must
# never grow unboundedly waiting for labels that never come
MAX_PENDING_BATCHES = 64


class LatencyWindow:
    """Time-windowed latency reservoir: ``add()`` per sample,
    ``snapshot()`` prunes to the window and reports recent-traffic
    percentiles. Capped so a window of pathological traffic stays
    bounded (oldest samples drop first — the percentile bias is toward
    RECENT traffic, which is the point)."""

    def __init__(self, window_s: float = 30.0, cap: int = 100_000):
        self.window_s = float(window_s)
        self._cap = int(cap)
        self._samples: collections.deque = collections.deque()

    def add(self, ms: float, now: float | None = None) -> None:
        now = time.time() if now is None else now
        self._samples.append((now, float(ms)))
        while len(self._samples) > self._cap:
            self._samples.popleft()

    def prune(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        cutoff = now - self.window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def snapshot(self, now: float | None = None) -> dict:
        """{"count", "p50_ms", "p99_ms", "max_ms"} over the window
        (count 0 and no percentiles when the window is empty)."""
        self.prune(now)
        if not self._samples:
            return {"count": 0}
        lats = np.asarray([ms for _, ms in self._samples])
        return {"count": int(lats.size),
                "p50_ms": float(np.percentile(lats, 50)),
                "p99_ms": float(np.percentile(lats, 99)),
                "max_ms": float(lats.max())}

    def hedge_threshold_ms(self, factor: float, *, min_count: int = 20,
                           floor_ms: float = 1.0,
                           now: float | None = None) -> float | None:
        """p99-derived hedging trigger (serving/router.py): ``factor`` x
        the windowed p99, or None while the window holds fewer than
        ``min_count`` samples — a threshold derived off a handful of
        samples would hedge on noise, doubling load exactly when the
        estimate is worst. ``floor_ms`` keeps a microsecond-fast bench
        window from hedging every request."""
        if factor <= 0.0:
            return None
        snap = self.snapshot(now)
        if snap["count"] < int(min_count):
            return None
        return max(float(floor_ms), float(factor) * float(snap["p99_ms"]))


class VersionStats:
    """One served version's window: latency, scores, pending labels."""

    __slots__ = ("version", "role", "latency", "requests", "hist",
                 "score_sum", "score_count", "pending", "pending_dropped")

    def __init__(self, version: int, role: str,
                 window_s: float = 30.0):
        self.version = int(version)
        self.role = str(role)
        self.latency = LatencyWindow(window_s)
        self.requests = 0                       # scored batches' examples
        self.hist = np.zeros(SCORE_BUCKETS, dtype=np.int64)
        self.score_sum = 0.0
        self.score_count = 0
        self.pending: collections.deque = collections.deque()
        self.pending_dropped = 0

    def record(self, scores, lat_ms: float,
               now: float | None = None) -> None:
        s = np.asarray(scores, dtype=np.float64).reshape(-1)
        self.requests += int(s.size)
        self.latency.add(lat_ms, now)
        idx = np.clip((s * SCORE_BUCKETS).astype(np.int64), 0,
                      SCORE_BUCKETS - 1)
        np.add.at(self.hist, idx, 1)
        self.score_sum += float(s.sum())
        self.score_count += int(s.size)
        self.pending.append(s)
        while len(self.pending) > MAX_PENDING_BATCHES:
            self.pending.popleft()
            self.pending_dropped += 1

    def pop_pending(self, n: int):
        """Oldest pending score batch of length ``n`` (label join is
        batch-for-batch in arrival order), or None."""
        for i, s in enumerate(self.pending):
            if s.size == n:
                del self.pending[i]
                return s
        return None

    def reset_window(self) -> None:
        self.requests = 0
        self.hist[:] = 0
        self.score_sum = 0.0
        self.score_count = 0


def score_kl(p_hist: np.ndarray, q_hist: np.ndarray) -> float:
    """KL(p || q) between two score histograms with epsilon smoothing —
    the distribution-drift half of the version-regression rule (AUC
    needs labels; the KL fires on label-free drift too)."""
    p = np.asarray(p_hist, dtype=np.float64) + _KL_EPS
    q = np.asarray(q_hist, dtype=np.float64) + _KL_EPS
    p /= p.sum()
    q /= q.sum()
    return float(np.sum(p * np.log(p / q)))


class ServingObs:
    """The server's per-window serving-observability bookkeeping."""

    def __init__(self, window_s: float | None = None,
                 slo_ms: float | None = None):
        self.window_s = float(flags.serving_window_s
                              if window_s is None else window_s)
        self.slo_ms = float(flags.serving_slo_ms
                            if slo_ms is None else slo_ms)
        self.versions: dict[int, VersionStats] = {}
        self.metrics = MetricRegistry()
        self.total = LatencyWindow(self.window_s or 30.0)
        self.served = 0                       # served examples, window
        self.window_start = time.time()
        self.windows_committed = 0

    # -- write side (server request/poll threads, under the caller's
    # lock) --------------------------------------------------------------

    def ensure_version(self, version: int, role: str) -> VersionStats:
        vs = self.versions.get(int(version))
        if vs is None:
            vs = VersionStats(version, role, self.window_s or 30.0)
            self.versions[int(version)] = vs
            self.metrics.init_metric(f"v{int(version)}", method="plain",
                                     phase=-1)
        vs.role = str(role)
        return vs

    def drop_version(self, version: int) -> None:
        self.versions.pop(int(version), None)

    def record(self, version: int, role: str, scores, lat_ms: float,
               served: bool, now: float | None = None) -> None:
        """One scored batch on ``version``: ``served`` marks the copy
        whose answer went back to the caller (shadow scoring records
        latency/scores but not serving volume)."""
        self.ensure_version(version, role).record(scores, lat_ms, now)
        if served:
            self.total.add(lat_ms, now)
            self.served += int(np.asarray(scores).reshape(-1).size)

    def observe_labels(self, labels, version: int | None = None,
                       preds=None) -> dict:
        """Join delayed labels back to pending scores and feed the
        per-version AUC. With explicit ``preds`` + ``version`` the join
        is the caller's; otherwise the oldest pending batch of matching
        length on EVERY version that scored it (shadow mode scores one
        request batch on both versions) is consumed. Returns
        {version: joined_count}."""
        lab = np.asarray(labels, dtype=np.float64).reshape(-1)
        joined: dict[int, int] = {}
        if preds is not None and version is not None:
            self.ensure_version(version, self.versions[int(version)].role
                                if int(version) in self.versions
                                else "stable")
            self.metrics.add_data(f"v{int(version)}", np.asarray(preds),
                                  lab)
            joined[int(version)] = int(lab.size)
            return joined
        for vid, vs in self.versions.items():
            s = vs.pop_pending(int(lab.size))
            if s is None:
                continue
            self.metrics.add_data(f"v{vid}", s, lab)
            joined[vid] = int(lab.size)
        return joined

    # -- read side --------------------------------------------------------

    def due(self, now: float | None = None) -> bool:
        if self.window_s <= 0:
            return False
        now = time.time() if now is None else now
        return (now - self.window_start) >= self.window_s

    def version_fields(self) -> dict:
        """Per-version attribution for the record's ``versions`` object
        (and /healthz): role, windowed latency, score mean, AUC when
        labels have arrived, candidate-vs-stable score KL."""
        stable = next((v for v in self.versions.values()
                       if v.role == "stable"), None)
        out: dict[str, dict] = {}
        for vid, vs in self.versions.items():
            snap = vs.latency.snapshot()
            entry: dict = {"role": vs.role,
                           "requests": int(vs.requests)}
            if snap["count"]:
                entry["p50_ms"] = snap["p50_ms"]
                entry["p99_ms"] = snap["p99_ms"]
            if vs.score_count:
                entry["score_mean"] = vs.score_sum / vs.score_count
            msg = self.metrics.get_metric_msg(f"v{vid}")
            if msg.get("size", 0) > 0 and msg.get("auc", -1) >= 0:
                entry["auc"] = float(msg["auc"])
            if (vs.role == "candidate" and stable is not None
                    and vs.score_count and stable.score_count):
                entry["score_kl"] = score_kl(vs.hist, stable.hist)
            if vs.pending_dropped:
                entry["pending_dropped"] = int(vs.pending_dropped)
            out[str(vid)] = entry
        return out

    def commit(self, now: float | None = None, **extra) -> dict:
        """Close the window: build the serving record's fields (the
        caller emits them as ``type="serving_record"`` and merges its
        own counters — swaps, version_lag, failures, replica hits — via
        ``extra``), then reset the window accumulators. AUC states and
        pending-label FIFOs survive commits (labels are delayed)."""
        now = time.time() if now is None else now
        snap = self.total.snapshot(now)
        fields = {
            "window_s": round(now - self.window_start, 3),
            "requests": int(self.served),
            "failures": 0,
            "swaps": 0,
            "version_lag": 0,
            "slo_ms": float(self.slo_ms),
            "p50_ms": float(snap.get("p50_ms", 0.0)),
            "p99_ms": float(snap.get("p99_ms", 0.0)),
            "versions": self.version_fields(),
        }
        for k, v in extra.items():
            if v is not None:
                fields[k] = v
        for vs in self.versions.values():
            vs.reset_window()
        self.served = 0
        self.window_start = now
        self.windows_committed += 1
        return fields

