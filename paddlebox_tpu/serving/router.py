"""Health-aware request router over a serving replica fleet (ISSUE 20).

One host runs N replicas off one donefile (serving/fleet.py); this module
is the dispatch layer in front of them — the piece that turns "a replica
died mid-swap" from an outage into a routing decision:

- **Eligibility off /healthz.** Per-replica health is polled (and cached
  for ``health_ttl_s``) through the same ``health()`` payload the
  operator curls: ``ok`` replicas take traffic; ``stale``/``degraded``/
  ``empty``/unreachable replicas fall out of rotation, and so does a
  replica whose ``building`` bit is set — swap-aware draining: a replica
  rebuilding a version drains instead of serving a request into its
  build window. Draining is a preference, not a death sentence: when NO
  ok replica remains, a building or stale replica that still holds an
  active version serves as the fallback — a build does not unload the
  active model (the swap is atomic), and old scores beat a shed.
- **Least-loaded-of-two-choices.** Two random eligible replicas, the one
  with fewer inflight requests wins — the classic power-of-two-choices
  balance without a global queue.
- **Shed, never hang.** No serviceable replica → :class:`RouterShedError`
  (the 503 of this stack): a NAMED refusal carrying every replica's
  status, counted in :meth:`stats`. When every replica is merely stale
  (publishes stopped; nothing is *wrong* with the models) the router
  degrades to the freshest stale replica instead — serving yesterday's
  model beats serving nothing — and emits ``fleet.serving_stale``.
- **One bounded retry.** A dispatch failure or per-request timeout gets
  exactly ONE retry on a DIFFERENT replica (the failed one is excluded —
  retrying into the replica that just timed out would double its pain).
  No retry storms: one request costs at most two dispatches (plus at
  most one hedge).
- **Hedged requests.** With ``flags.serving_hedge_factor`` > 0, a
  request outstanding past factor x the router's windowed p99 launches a
  second copy on another replica; first answer wins, the loser is
  cancelled and its late result discarded (counted, never returned) —
  the tail-latency insurance the ``serving_fleet`` bench gate holds
  under an injected slow replica. The trigger derives from a
  SERVICE-TIME window that excludes hedge-won requests: a rescued
  request's client latency is ~the threshold itself, and feeding it
  back would ratchet the threshold by factor-x per slow request until
  hedging self-disables exactly when one replica goes slow. Hedge-LOST
  samples stay in: when the whole fleet is slow the hedge buys nothing,
  and the rising threshold is the built-in backoff.

Replica handles are duck-typed (serving/fleet.py LocalReplica /
SubprocessReplica): ``name``, ``quarantined``, ``inflight``,
``health() -> dict``, ``submit(ids, mask, dense) -> Future``.

``serving.fleet.router.pre_dispatch`` (utils/faultpoint.py) sits on the
PRIMARY dispatch only — its ioerror leg proves a faulted dispatch is
retried on another replica, not surfaced to the caller.
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import FIRST_COMPLETED, Future, wait
from concurrent.futures import TimeoutError as FutureTimeoutError

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags
from paddlebox_tpu.serving.obs import LatencyWindow
from paddlebox_tpu.utils import faultpoint


class RouterShedError(RuntimeError):
    """No serviceable replica: the request is REFUSED (counted, named) —
    the router's contract is that a caller is never left hanging on a
    fleet that cannot answer."""


class RouterTimeoutError(TimeoutError):
    """One replica dispatch exceeded the per-request timeout. Internal
    to the retry path unless the retry times out too."""


class Router:
    """Health-aware least-loaded-of-two-choices dispatcher over replica
    handles. One instance per host fleet; thread-safe."""

    def __init__(self, replicas, *, timeout_s: float = 5.0,
                 health_ttl_s: float = 1.0,
                 hedge_factor: float | None = None,
                 hedge_min_count: int = 20,
                 window_s: float | None = None,
                 rng: random.Random | None = None):
        self.replicas = list(replicas)
        self.timeout_s = float(timeout_s)
        self.health_ttl_s = float(health_ttl_s)
        # 0.0 = hedging off; the flag is the fleet-wide default, the
        # kwarg the bench/test override
        self.hedge_factor = (float(flags.serving_hedge_factor)
                             if hedge_factor is None
                             else float(hedge_factor))
        self.hedge_min_count = int(hedge_min_count)
        win = (float(flags.serving_window_s or 30.0)
               if window_s is None else float(window_s))
        self._lat = LatencyWindow(win)
        # hedge-threshold source: client-observed latency MINUS the
        # hedge-won requests (see the module docstring's ratchet note)
        self._lat_svc = LatencyWindow(win)
        self._lock = threading.Lock()
        self._rng = rng if rng is not None else random.Random()
        self._health_cache: dict[str, tuple[float, dict]] = {}
        self._stale_emit_ts = 0.0
        self._requests = 0
        self._sheds = 0
        self._degraded_dispatches = 0
        self._retries = 0
        self._timeouts = 0
        self._failures = 0
        self._hedges = 0
        self._hedges_won = 0
        self._hedge_discards = 0

    # ---- health / eligibility -------------------------------------------

    def _health(self, rep, now: float) -> dict:
        with self._lock:
            cached = self._health_cache.get(rep.name)
            if cached is not None and now - cached[0] < self.health_ttl_s:
                return cached[1]
        try:
            h = rep.health()
        except Exception as e:   # noqa: BLE001 — a dead replica is a
            # routing fact, not a router error
            h = {"status": "unreachable", "error": repr(e)}
        with self._lock:
            self._health_cache[rep.name] = (now, h)
        return h

    def invalidate_health(self, name: str | None = None) -> None:
        """Drop cached health (all replicas with no argument) — the
        fleet calls this after a restart/quarantine so rotation reacts
        within the tick, not the TTL."""
        with self._lock:
            if name is None:
                self._health_cache.clear()
            else:
                self._health_cache.pop(name, None)

    def _survey(self, now: float):
        """(eligible, fallback, statuses): eligible replicas are ok +
        not building + not quarantined; the fallback list holds every
        replica that still has an active version to serve (building or
        stale — a build does not unload the active model, the swap is
        atomic), sorted freshest first."""
        eligible, fallback, statuses = [], [], {}
        for rep in self.replicas:
            if getattr(rep, "quarantined", False):
                statuses[rep.name] = "quarantined"
                continue
            h = self._health(rep, now)
            status = str(h.get("status", "unreachable"))
            building = bool(h.get("building"))
            statuses[rep.name] = (status + "+building" if building
                                  else status)
            if status == "ok" and not building:
                eligible.append(rep)
            elif (status in ("ok", "stale", "degraded")
                    and h.get("active_version") is not None):
                age = h.get("age_seconds")
                fallback.append((float("inf") if age is None
                                 else float(age), rep))
        fallback.sort(key=lambda t: t[0])
        return eligible, [r for _, r in fallback], statuses

    def _pick(self, exclude: set[str] | None = None):
        """One replica by two-choice least-loaded over the eligible set
        (minus ``exclude``); degrade to the freshest stale replica when
        nothing is ok; RouterShedError when nothing can serve at all."""
        now = time.time()
        exclude = exclude or set()
        eligible, stale, statuses = self._survey(now)
        eligible = [r for r in eligible if r.name not in exclude]
        if not eligible:
            stale = [r for r in stale if r.name not in exclude]
            if stale:
                # fallback dispatch: every replica is building or stale,
                # but the freshest one still SERVES (a build keeps the
                # old version active; the swap is atomic) and serving it
                # beats a shed. The staleness alert fires only when the
                # fleet is actually stale — a transient build window is
                # not an incident — and once per TTL, not per request.
                chosen = stale[0]
                with self._lock:
                    self._degraded_dispatches += 1
                    emit = (not statuses.get(chosen.name, "").startswith(
                                "ok")
                            and now - self._stale_emit_ts
                            >= self.health_ttl_s)
                    if emit:
                        self._stale_emit_ts = now
                if emit:
                    monitor.counter_add("fleet.serving_stale")
                    monitor.event("fleet.serving_stale",
                                  statuses=statuses,
                                  chosen=chosen.name)
                return chosen
            with self._lock:
                self._sheds += 1
            monitor.counter_add("fleet.router_sheds")
            raise RouterShedError(
                f"no serviceable replica (shed): {statuses}"
                + (f"; excluded after failure: {sorted(exclude)}"
                   if exclude else ""))
        if len(eligible) == 1:
            return eligible[0]
        a, b = self._rng.sample(eligible, 2)
        return a if a.inflight <= b.inflight else b

    # ---- dispatch --------------------------------------------------------

    def score(self, ids, mask, dense=None,
              timeout_s: float | None = None):
        """Route one request: pick → dispatch → (maybe hedge) → answer,
        with ONE retry on a different replica after a dispatch failure
        or timeout. Raises RouterShedError / RouterTimeoutError / the
        replica's scoring exception (after the retry also failed)."""
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        t0 = time.perf_counter()
        with self._lock:
            self._requests += 1
        tried: set[str] = set()
        state = {"hedge_won": False}
        try:
            out = self._attempt(ids, mask, dense, timeout, tried,
                                primary=True, state=state)
        except RouterShedError:
            raise                     # nothing to retry INTO
        except Exception:
            # ONE bounded retry on a replica that did not just fail —
            # `tried` carries the primary (and any hedge) target, so
            # the retry can never land on the replica that timed out
            with self._lock:
                self._retries += 1
            monitor.counter_add("fleet.router_retries")
            try:
                out = self._attempt(ids, mask, dense, timeout, tried,
                                    primary=False, state=state)
            except Exception:
                with self._lock:
                    self._failures += 1
                monitor.counter_add("fleet.router_failures")
                raise
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:              # LatencyWindow is not thread-safe
            self._lat.add(elapsed_ms)
            if not state["hedge_won"]:
                self._lat_svc.add(elapsed_ms)
        return out

    def _attempt(self, ids, mask, dense, timeout: float,
                 tried: set[str], *, primary: bool, state: dict):
        rep = self._pick(exclude=tried)
        tried.add(rep.name)
        if primary:
            # the registered crash window: a request is about to
            # dispatch to its chosen replica. Primary only — the armed
            # ioerror leg proves the retry lands elsewhere; hitting it
            # again on the retry would turn one injected fault into an
            # unconditional request failure.
            faultpoint.hit("serving.fleet.router.pre_dispatch")
        fut: Future = rep.submit(ids, mask, dense)
        deadline = time.monotonic() + timeout
        if primary:
            with self._lock:          # LatencyWindow is not thread-safe
                thr_ms = self._lat_svc.hedge_threshold_ms(
                    self.hedge_factor, min_count=self.hedge_min_count)
        else:
            thr_ms = None
        if thr_ms is not None:
            done, _ = wait([fut], timeout=min(thr_ms / 1e3, timeout))
            if fut not in done:
                out = self._hedge(rep, fut, ids, mask, dense, deadline,
                                  tried, state)
                if out is not _NO_HEDGE:
                    return out
        try:
            return fut.result(timeout=max(0.0,
                                          deadline - time.monotonic()))
        except (TimeoutError, FutureTimeoutError):
            fut.cancel()
            with self._lock:
                self._timeouts += 1
            monitor.counter_add("fleet.router_timeouts")
            raise RouterTimeoutError(
                f"replica {rep.name} exceeded {timeout:.3f}s") from None

    def _hedge(self, rep, fut: Future, ids, mask, dense,
               deadline: float, tried: set[str], state: dict):
        """Launch the hedge and race it against the primary. Returns the
        winner's result, or ``_NO_HEDGE`` when no second replica exists
        (the caller falls back to waiting on the primary alone)."""
        try:
            other = self._pick(exclude={rep.name})
        except RouterShedError:
            return _NO_HEDGE          # nobody to hedge onto
        # a timeout below times BOTH racers out — the one retry must
        # land on a third replica, never the hedge target that just
        # failed to answer either
        tried.add(other.name)
        with self._lock:
            self._hedges += 1
        monitor.counter_add("fleet.router_hedges")
        hfut: Future = other.submit(ids, mask, dense)
        racers = {fut: rep, hfut: other}
        last_err: Exception | None = None
        while racers:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            done, _ = wait(list(racers), timeout=remaining,
                           return_when=FIRST_COMPLETED)
            if not done:
                break
            winner = done.pop()
            try:
                out = winner.result()
            except Exception as e:   # noqa: BLE001 — the OTHER racer
                last_err = e          # may still answer; a hedge
                del racers[winner]    # exists exactly to survive this
                continue
            loser = next((f for f in racers if f is not winner), None)
            if loser is not None:
                self._discard(loser)
            if winner is hfut:
                state["hedge_won"] = True
                with self._lock:
                    self._hedges_won += 1
                monitor.counter_add("fleet.router_hedges_won")
            return out
        if not racers and last_err is not None:
            raise last_err            # both racers FAILED (not a timeout)
        # both racers timed out: cancel and let the caller's
        # timeout/retry accounting take over
        for f in list(racers):
            self._discard(f, count=False)
        with self._lock:
            self._timeouts += 1
        monitor.counter_add("fleet.router_timeouts")
        raise RouterTimeoutError(
            f"primary {rep.name} and hedge both exceeded the deadline")

    def _discard(self, fut: Future, count: bool = True) -> None:
        """Cancel the losing racer; a loser past cancel (already
        running) resolves later — its result is DISCARDED by contract
        (never returned to any caller) and counted, because a late
        loser silently winning would un-order the first-wins race."""
        if fut.cancel():
            return

        def _count(_f):
            if count:
                with self._lock:
                    self._hedge_discards += 1
        fut.add_done_callback(_count)

    # ---- accounting ------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            snap = self._lat.snapshot()
            out = {
                "replicas": len(self.replicas),
                "requests": int(self._requests),
                "sheds": int(self._sheds),
                "degraded_dispatches": int(self._degraded_dispatches),
                "retries": int(self._retries),
                "timeouts": int(self._timeouts),
                "failures": int(self._failures),
                "hedges": int(self._hedges),
                "hedges_won": int(self._hedges_won),
                "hedge_discards": int(self._hedge_discards),
            }
        if snap["count"]:
            out["p50_ms"] = round(snap["p50_ms"], 3)
            out["p99_ms"] = round(snap["p99_ms"], 3)
        return out


_NO_HEDGE = object()
