"""Serving replica fleet — supervision, shared staging, auto-promotion.

One serving host runs N replicas of the same donefile (ISSUE 20; the
reference's ad-serving hosts run several scoring workers per machine so a
hot-swap or a crash never takes the whole host out of rotation). This
module is the host-side supervisor around serving/server.py:

- :class:`SharedStagingCache` — ONE download + CRC-verify per version per
  host. Replicas race for a per-version lease (an atomic hard-link
  create, the same discipline as every donefile/manifest writer); the
  winner downloads into a tmp name, verifies the manifest, and
  atomically renames the verified copy into place (tmp → fsync → rename
  → dir fsync). Losers wait on the final name. A lease-holder that dies
  mid-download (``serving.fleet.lease.pre_verify``) leaves a lease whose
  mtime stops advancing — expiry detection, takeover, and the orphaned
  tmp is swept; the host still ends with exactly one verified copy.
- :class:`ReplicaFleet` — spawns N :class:`SubprocessReplica` workers off
  one root, restarts a crashed replica with bounded exponential backoff,
  and QUARANTINES a replica that crash-loops on the same announced
  version (fail-stop → fail-over: the router routes around it; the
  version is the fault, restarting forever would burn the host). Fleet
  state goes out each window as a schema-checked ``fleet_record``
  (monitor/flight.validate_fleet_record) that aggregate merges into the
  world view and the doctor's ``fleet-degraded`` rule reads.
- :class:`PromotionGovernor` — verdict-guarded auto-promotion
  (``flags.serving_auto_promote``): the doctor's version-regression rule
  evaluates each serving window; a CRITICAL "do not promote" verdict
  HOLDS the candidate fleet-wide and quarantines that version
  (``fleet_promote_hold`` + ``fleet_version_quarantined``); only K =
  ``flags.serving_promote_windows`` consecutive clean windows promote —
  ``promote_candidate()`` on every replica, ``fleet_promoted``.

Runbook (README "Serving fleet runbook")::

    python -m paddlebox_tpu.serving.fleet ROOT --replicas 2

spawns the replicas (``--serve-replica`` is the internal per-replica
entrypoint: FleetReplicaServer + an HTTP endpoint serving /healthz,
/metrics, /score, /promote) and supervises them until interrupted.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags
from paddlebox_tpu.monitor import context as mon_ctx
from paddlebox_tpu.monitor import doctor as doctor_lib
from paddlebox_tpu.serving.server import ServingServer
from paddlebox_tpu.utils import checkpoint as ckpt_lib
from paddlebox_tpu.utils import faultpoint
from paddlebox_tpu.utils import fs as fs_lib
from paddlebox_tpu.utils.checkpoint import CheckpointCorruptError


# ---------------------------------------------------------------------------
# shared staging: one download + verify per version per host
# ---------------------------------------------------------------------------

class SharedStagingCache:
    """Per-host staging directory shared by every replica.

    Layout::

        <root>/versions/<name>           the verified copies (final names)
        <root>/versions/.tmp.<name>.<pid>  an in-flight download
        <root>/leases/<name>.lease       the download lease

    The lease is an atomic hard-link create (``os.link`` of a unique tmp
    onto the lease name: succeeds for exactly one process). The holder
    touches it before the verify so a long download keeps it fresh; a
    holder that died stops touching it, the mtime ages past
    ``lease_ttl_s``, and a waiting replica unlinks + retakes it
    (``fleet_lease_retaken``), sweeping the dead holder's tmp. The final
    name only ever appears via rename-after-verify, so a reader can
    trust any directory it finds under it.
    """

    def __init__(self, root: str, *, lease_ttl_s: float = 30.0,
                 poll_s: float = 0.05, wait_timeout_s: float = 120.0):
        self.root = os.path.abspath(root)
        self.lease_ttl_s = float(lease_ttl_s)
        self.poll_s = float(poll_s)
        self.wait_timeout_s = float(wait_timeout_s)
        self.versions_dir = os.path.join(self.root, "versions")
        self.leases_dir = os.path.join(self.root, "leases")
        os.makedirs(self.versions_dir, exist_ok=True)
        os.makedirs(self.leases_dir, exist_ok=True)
        self.downloads = 0             # this process fetched + verified
        self.cache_hits = 0            # final name already present
        self.lease_waits = 0           # waited on another holder
        self.lease_retakes = 0         # took over an expired lease

    # -- lease primitives --------------------------------------------------

    def _lease_path(self, name: str) -> str:
        return os.path.join(self.leases_dir, f"{name}.lease")

    def _try_acquire(self, name: str) -> bool:
        """Atomically create the lease file; True iff WE hold it now."""
        lease = self._lease_path(name)
        probe = f"{lease}.probe.{os.getpid()}"
        with open(probe, "w") as f:
            f.write(json.dumps({"pid": os.getpid(), "ts": time.time()}))
        try:
            os.link(probe, lease)      # atomic: exactly one winner
            return True
        except FileExistsError:
            return False
        finally:
            os.unlink(probe)

    def _lease_age(self, name: str) -> float | None:
        try:
            return time.time() - os.stat(self._lease_path(name)).st_mtime
        except FileNotFoundError:
            return None

    def _release(self, name: str) -> None:
        try:
            os.unlink(self._lease_path(name))
        # pblint: disable=silent-except -- expired + retaken under us:
        # the lease is gone, which is exactly what release wants
        except FileNotFoundError:
            pass

    def _sweep_tmp(self, name: str) -> None:
        """Remove orphaned in-flight copies of ``name`` (a dead holder's
        partial download) — takeover starts from clean bytes."""
        prefix = f".tmp.{name}."
        for entry in os.listdir(self.versions_dir):
            if entry.startswith(prefix):
                shutil.rmtree(os.path.join(self.versions_dir, entry),
                              ignore_errors=True)

    # -- the one public operation -----------------------------------------

    def materialize(self, path: str) -> str:
        """A verified local copy of artifact ``path`` under the shared
        staging dir; downloads (or copies) + verifies at most once per
        version per host, however many replicas ask concurrently."""
        name = os.path.basename(path.rstrip("/"))
        final = os.path.join(self.versions_dir, name)
        deadline = time.monotonic() + self.wait_timeout_s
        waited = False
        while True:
            if os.path.isdir(final):
                self.cache_hits += 1
                return final
            if self._try_acquire(name):
                break
            # someone else holds the download lease: wait for the final
            # name — unless the holder died and the lease went stale
            waited = True
            age = self._lease_age(name)
            if age is not None and age > self.lease_ttl_s:
                self._release(name)    # expire it; next loop re-races
                self.lease_retakes += 1
                monitor.counter_add("fleet.lease_retakes")
                monitor.event("fleet_lease_retaken", version=name,
                              stale_age_s=round(age, 3))
                continue
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"gave up waiting for staging of {name!r} after "
                    f"{self.wait_timeout_s}s (lease age {age})")
            time.sleep(self.poll_s)
        if waited:
            self.lease_waits += 1
        try:
            # the final name may have landed between our last check and
            # the acquire (the previous holder finished first)
            if os.path.isdir(final):
                self.cache_hits += 1
                return final
            self._sweep_tmp(name)      # a dead holder's partial bytes
            tmp = os.path.join(self.versions_dir,
                               f".tmp.{name}.{os.getpid()}")
            if fs_lib.is_remote(path):
                fs_lib.resolve(path)[0].get(path, tmp)
            else:
                shutil.copytree(path, tmp)
            # long fetch done: refresh the lease so the verify below
            # cannot be raced by an expiry-takeover
            os.utime(self._lease_path(name))
            # the registered crash window: bytes staged, verify + rename
            # not yet run — dying here must leave the lease expirable
            # and never a torn copy under the final name
            faultpoint.hit("serving.fleet.lease.pre_verify")
            try:
                ckpt_lib.verify_manifest(tmp)
            except CheckpointCorruptError:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            os.rename(tmp, final)      # atomic: verified bytes only
            dfd = os.open(self.versions_dir, os.O_RDONLY)
            try:
                os.fsync(dfd)          # make the rename itself durable
            finally:
                os.close(dfd)
            self.downloads += 1
            monitor.counter_add("fleet.staging_downloads")
            return final
        finally:
            self._release(name)

    def stats(self) -> dict:
        return {"downloads": self.downloads,
                "cache_hits": self.cache_hits,
                "lease_waits": self.lease_waits,
                "lease_retakes": self.lease_retakes}


# ---------------------------------------------------------------------------
# replica handles (the router's duck type)
# ---------------------------------------------------------------------------

class FleetReplicaServer(ServingServer):
    """A ServingServer with the fleet's build crash window on its swap
    path (the replica-killed-mid-swap leg of the kill matrix)."""

    def _build(self, loaded, entry):
        faultpoint.hit("serving.fleet.replica.pre_build")
        return super()._build(loaded, entry)


class LocalReplica:
    """In-process replica: a FleetReplicaServer + BatchingFrontend pair
    behind the router's handle protocol (bench + unit tests; the real
    fleet runs SubprocessReplica)."""

    def __init__(self, name: str, server: ServingServer, frontend):
        self.name = name
        self.server = server
        self.frontend = frontend
        self.quarantined = False

    @property
    def inflight(self) -> int:
        return self.frontend.inflight

    def health(self) -> dict:
        return self.server.health()

    def submit(self, ids, mask, dense=None) -> Future:
        return self.frontend.submit(ids, mask, dense)

    def promote(self) -> bool:
        return self.server.promote_candidate()


class SubprocessReplica:
    """One replica OS process (the ``--serve-replica`` entrypoint) plus
    the HTTP client side of the router's handle protocol. The process
    boundary is the point: a kill drops exactly this replica."""

    def __init__(self, index: int, root: str, *, staging_root: str,
                 workdir: str, poll_s: float = 0.2,
                 extra_env: dict | None = None,
                 spawn_timeout_s: float = 60.0):
        self.index = int(index)
        self.name = f"replica-{index}"
        self.root = root
        self.staging_root = staging_root
        self.workdir = workdir
        self.poll_s = float(poll_s)
        self.extra_env = dict(extra_env or {})
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.quarantined = False
        self.proc: subprocess.Popen | None = None
        self.port: int | None = None
        self.exits: list[int] = []
        self._inflight = 0
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix=f"{self.name}-client")
        os.makedirs(workdir, exist_ok=True)

    # -- lifecycle ---------------------------------------------------------

    def spawn(self) -> "SubprocessReplica":
        port_file = os.path.join(self.workdir, f"{self.name}.port.json")
        try:
            os.unlink(port_file)
        # pblint: disable=silent-except -- first spawn has no stale
        # port file to clear; nothing was lost
        except FileNotFoundError:
            pass
        env = dict(os.environ)
        env.update(self.extra_env)
        log = open(os.path.join(self.workdir, f"{self.name}.log"), "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "paddlebox_tpu.serving.fleet",
             "--serve-replica", self.root,
             "--staging-root", self.staging_root,
             "--port-file", port_file,
             "--poll-s", str(self.poll_s)],
            env=env, stdout=log, stderr=log)
        log.close()                    # the child holds its own handle
        deadline = time.monotonic() + self.spawn_timeout_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"{self.name} exited {self.proc.returncode} before "
                    f"publishing its port (see {self.name}.log)")
            if os.path.exists(port_file):
                with open(port_file) as f:
                    self.port = int(json.load(f)["port"])
                return self
            time.sleep(0.02)
        raise TimeoutError(f"{self.name} never published its port")

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)
        self._pool.shutdown(wait=False, cancel_futures=True)

    # -- router handle protocol -------------------------------------------

    @property
    def inflight(self) -> int:
        return self._inflight

    def _url(self, path: str) -> str:
        return f"http://127.0.0.1:{self.port}{path}"

    def _get_json(self, path: str, timeout: float = 2.0) -> dict:
        with urllib.request.urlopen(self._url(path),
                                    timeout=timeout) as resp:
            return json.loads(resp.read().decode())

    def _post_json(self, path: str, payload: dict,
                   timeout: float = 30.0) -> dict:
        req = urllib.request.Request(
            self._url(path), data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            body = e.read().decode(errors="replace")[:300]
            raise RuntimeError(
                f"{self.name} {path} -> {e.code}: {body}") from e

    def health(self) -> dict:
        # /healthz answers 503 (with the same JSON body) before the
        # first load — "empty" is a health state, not a client error
        try:
            return self._get_json("/healthz")
        except urllib.error.HTTPError as e:
            return json.loads(e.read().decode())

    def submit(self, ids, mask, dense=None) -> Future:
        payload = {"ids": np.asarray(ids).tolist(),
                   "mask": np.asarray(mask).astype(int).tolist()}
        if dense is not None:
            payload["dense"] = np.asarray(dense).tolist()

        def _call():
            try:
                out = self._post_json("/score", payload)
                return np.asarray(out["scores"])
            finally:
                with self._lock:
                    self._inflight -= 1
        with self._lock:
            self._inflight += 1
        return self._pool.submit(_call)

    def promote(self) -> bool:
        return bool(self._post_json("/promote", {}).get("promoted"))


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------

class ReplicaFleet:
    """Spawns + supervises N SubprocessReplica workers off one root.

    Restart policy: a crashed replica restarts after a bounded
    exponential backoff (``backoff0_s`` doubling to ``backoff_max_s``);
    crash-looping ``max_restarts_per_version`` times while the SAME
    version is announced quarantines the replica — the version (not the
    machine) is the likely fault, and fail-over beats a restart storm.
    """

    def __init__(self, root: str, *, replicas: int | None = None,
                 staging_root: str | None = None,
                 workdir: str | None = None, poll_s: float = 0.2,
                 backoff0_s: float = 0.5, backoff_max_s: float = 10.0,
                 max_restarts_per_version: int = 3,
                 window_s: float | None = None,
                 replica_env=None, supervise_tick_s: float = 0.1):
        # flags.serving_fleet_replicas is the deploy-wide default; the
        # kwarg is the bench/test override
        self.n = int(flags.serving_fleet_replicas
                     if replicas is None else replicas)
        if self.n < 1:
            raise ValueError(f"fleet needs >=1 replica, got {self.n}")
        self.root = root
        base = workdir or os.path.join(".", "fleet_work")
        self.workdir = os.path.abspath(base)
        self.staging_root = os.path.abspath(
            staging_root or os.path.join(self.workdir, "staging"))
        self.poll_s = float(poll_s)
        self.backoff0_s = float(backoff0_s)
        self.backoff_max_s = float(backoff_max_s)
        self.max_restarts_per_version = int(max_restarts_per_version)
        self.window_s = float(flags.serving_window_s
                              if window_s is None else window_s)
        self.supervise_tick_s = float(supervise_tick_s)
        self._replica_env = replica_env or (lambda i: {})
        self.replicas: list[SubprocessReplica] = [
            SubprocessReplica(
                i, root, staging_root=self.staging_root,
                workdir=self.workdir, poll_s=poll_s,
                extra_env=self._replica_env(i))
            for i in range(self.n)]
        self.router = None             # attach_router()
        self.governor = None           # attach_governor()
        self.restarts = 0
        self._restarts_by_version: dict[int, dict] = {
            i: {} for i in range(self.n)}
        self._next_spawn: dict[int, float] = {}
        self._last_health: dict[int, dict] = {}
        self._window_start = time.time()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def attach_router(self, router) -> None:
        """The router whose dispatch stats ride the fleet_record."""
        self.router = router

    def attach_governor(self, governor) -> None:
        self.governor = governor

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicaFleet":
        for r in self.replicas:
            r.spawn()
        self._stop.clear()
        self._thread = mon_ctx.spawn(self._supervise,
                                     name="fleet-supervisor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        for r in self.replicas:
            r.stop()

    # -- supervision -------------------------------------------------------

    def _supervise(self) -> None:
        while not self._stop.is_set():
            try:
                self.supervise_once()
            except Exception as e:   # noqa: BLE001 — the supervisor's
                # job under failure is to keep supervising
                monitor.counter_add("fleet.supervise_errors")
                monitor.event("fleet_supervise_error", error=repr(e))
            self.commit_window()       # due-gated; no-op early
            self._stop.wait(self.supervise_tick_s)

    def supervise_once(self) -> None:
        """One supervision tick (public for test-driven stepping):
        refresh health, detect exits, restart-with-backoff or
        quarantine."""
        now = time.monotonic()
        for r in self.replicas:
            if r.quarantined:
                continue
            if r.alive():
                try:
                    self._last_health[r.index] = r.health()
                # pblint: disable=silent-except -- a replica between
                # spawn and its HTTP bind answers nothing; liveness is
                # tracked by the process, health stays last-known
                except Exception:   # noqa: BLE001
                    pass
                continue
            due = self._next_spawn.get(r.index)
            if due is None:
                self._on_exit(r, now)
            elif now >= due:
                del self._next_spawn[r.index]
                try:
                    r.spawn()
                    if self.router is not None:
                        self.router.invalidate_health(r.name)
                except Exception as e:   # noqa: BLE001 — a failed
                    # respawn re-enters the backoff loop, it must not
                    # kill the supervisor
                    monitor.event("fleet_supervise_error",
                                  replica=r.name, error=repr(e))
                    self._on_exit(r, now)

    def _announced_version(self, index: int) -> int:
        h = self._last_health.get(index) or {}
        v = h.get("announced_version")
        return int(v) if isinstance(v, int) else -1

    def _on_exit(self, r: SubprocessReplica, now: float) -> None:
        code = r.proc.returncode if r.proc is not None else -1
        r.exits.append(int(code))
        version = self._announced_version(r.index)
        counts = self._restarts_by_version[r.index]
        counts[version] = counts.get(version, 0) + 1
        if self.router is not None:
            self.router.invalidate_health(r.name)
        if counts[version] > self.max_restarts_per_version:
            # crash-loop on ONE version: fail-stop this replica and let
            # the router fail traffic over to its peers — the version is
            # the repeating variable, restart #N+1 would die the same way
            r.quarantined = True
            monitor.counter_add("fleet.replica_quarantines")
            monitor.event("fleet_replica_quarantined", replica=r.name,
                          exit_code=int(code), version=version,
                          crashes=counts[version])
            return
        self.restarts += 1
        backoff = min(self.backoff_max_s,
                      self.backoff0_s * (2 ** (counts[version] - 1)))
        self._next_spawn[r.index] = now + backoff
        monitor.counter_add("fleet.replica_restarts")
        monitor.event("fleet_replica_restart", replica=r.name,
                      exit_code=int(code), version=version,
                      crashes=counts[version],
                      backoff_s=round(backoff, 3))

    # -- the fleet flight record ------------------------------------------

    def healthy_count(self) -> int:
        n = 0
        for r in self.replicas:
            if r.quarantined or not r.alive():
                continue
            h = self._last_health.get(r.index) or {}
            if h.get("status") == "ok":
                n += 1
        return n

    def commit_window(self, force: bool = False,
                      now: float | None = None) -> dict | None:
        """Emit one ``fleet_record`` when the window cadence is due
        (``force`` for test/bench stepping). None when not due or the
        cadence is off."""
        now = time.time() if now is None else now
        if not force and (self.window_s <= 0
                          or now - self._window_start < self.window_s):
            return None
        rs = (self.router.stats() if self.router is not None
              else {})
        fields = {
            "window_s": round(now - self._window_start, 3),
            "replicas": int(self.n),
            "healthy": int(self.healthy_count()),
            "quarantined": sum(1 for r in self.replicas
                               if r.quarantined),
            "requests": int(rs.get("requests", 0)),
            "sheds": int(rs.get("sheds", 0)),
            "retries": int(rs.get("retries", 0)),
            "hedges": int(rs.get("hedges", 0)),
            "hedges_won": int(rs.get("hedges_won", 0)),
            "restarts": int(self.restarts),
            "promote_holds": int(self.governor.promote_holds
                                 if self.governor is not None else 0),
            "p50_ms": float(rs.get("p50_ms", 0.0)),
            "p99_ms": float(rs.get("p99_ms", 0.0)),
        }
        self._window_start = now
        monitor.event("fleet_window", type="fleet_record", **fields)
        monitor.gauge_set("fleet.healthy_replicas", fields["healthy"])
        return fields


# ---------------------------------------------------------------------------
# verdict-guarded auto-promotion
# ---------------------------------------------------------------------------

class PromotionGovernor:
    """Drives ``promote_candidate()`` fleet-wide off the doctor's
    version-regression verdict (flags.serving_auto_promote).

    Feed it serving window records (each replica's ``commit_window``
    fields, or the aggregate's ``serving_records``) via :meth:`observe`.
    A CRITICAL verdict (the rule's "do not promote" suggestion) HOLDS
    the candidate and quarantines that version — it can never promote,
    even if later windows look clean (a regression that comes and goes
    is still a regression). Promotion requires K consecutive clean
    windows WITH signal: no-data windows reset nothing but do not count.
    """

    def __init__(self, replicas, *, windows: int | None = None,
                 history: int = 32):
        self.replicas = list(replicas)
        # flags.serving_promote_windows is the deploy default, the
        # kwarg the test override
        self.windows = int(flags.serving_promote_windows
                           if windows is None else windows)
        self.history = int(history)
        self.rule = doctor_lib.VersionRegressionRule()
        self._seen: list[dict] = []
        self.clean_windows = 0
        self.promote_holds = 0
        self.held_versions: set[int] = set()
        self.promoted_versions: list[int] = []

    def observe(self, serving_fields: dict) -> str:
        """One serving window record → the promotion decision for it:
        ``disabled`` | ``no-candidate`` | ``held`` | ``hold`` |
        ``no-data`` | ``clean`` | ``promoted``."""
        if not bool(flags.serving_auto_promote):
            return "disabled"
        self._seen.append(dict(serving_fields))
        del self._seen[:-self.history]
        cand = serving_fields.get("candidate_version")
        if cand is None:
            self.clean_windows = 0
            return "no-candidate"
        cand = int(cand)
        if cand in self.held_versions:
            return "held"
        status, finding = self.rule.evaluate(
            doctor_lib.DoctorContext(servings=list(self._seen)))
        if status == "fired" and finding["severity"] == "critical":
            # the rule's suggestion starts "do not promote" — enforce
            # it fleet-wide: hold + quarantine THIS version forever
            self.held_versions.add(cand)
            self.promote_holds += 1
            self.clean_windows = 0
            monitor.counter_add("fleet.promote_holds")
            monitor.event("fleet_promote_hold", version=cand,
                          rule=finding["rule"],
                          summary=finding["summary"][:300])
            monitor.event("fleet_version_quarantined", version=cand,
                          rule=finding["rule"])
            return "hold"
        if status == "fired":
            # warn (score-KL drift without an AUC gap): not promotable
            # evidence, not quarantine evidence — hold position
            self.clean_windows = 0
            return "hold"
        if status == "no-data":
            return "no-data"           # no signal: neither count nor reset
        self.clean_windows += 1
        if self.clean_windows < self.windows:
            return "clean"
        promoted = 0
        for r in self.replicas:
            try:
                promoted += bool(r.promote())
            except Exception as e:   # noqa: BLE001 — one unreachable
                # replica must not leave the fleet half-promoted forever;
                # its own tailer promotes on the next poll (split-off
                # path) and the event below names the miss
                monitor.event("fleet_supervise_error",
                              replica=getattr(r, "name", "?"),
                              error=f"promote failed: {e!r}")
        self.clean_windows = 0
        self.promoted_versions.append(cand)
        monitor.counter_add("fleet.promotions")
        monitor.event("fleet_promoted", version=cand,
                      replicas_promoted=int(promoted),
                      clean_windows=self.windows)
        return "promoted"


# ---------------------------------------------------------------------------
# CLI: fleet supervisor + the internal per-replica / stager entrypoints
# ---------------------------------------------------------------------------

def _serve_replica(args) -> int:
    """Internal entrypoint (one replica process): FleetReplicaServer off
    the shared staging cache + an HTTP endpoint with the router's
    surface (/healthz, /metrics, /score, /promote)."""
    import http.server

    cache = SharedStagingCache(args.staging_root)
    srv = FleetReplicaServer(args.root, poll_s=args.poll_s,
                             staging_cache=cache).start()

    class _Handler(http.server.BaseHTTPRequestHandler):
        def _send(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):   # noqa: N802 (stdlib API)
            if self.path.startswith("/healthz"):
                h = srv.health()
                h["staging"] = cache.stats()
                self._send(503 if srv.active is None else 200, h)
            elif self.path.startswith("/metrics"):
                body = monitor.hub().prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):   # noqa: N802 (stdlib API)
            n = int(self.headers.get("Content-Length") or 0)
            try:
                payload = json.loads(self.rfile.read(n) or b"{}")
            except json.JSONDecodeError as e:
                self._send(400, {"error": f"bad json: {e}"})
                return
            if self.path.startswith("/score"):
                try:
                    ids = np.asarray(payload["ids"], np.uint64)
                    mask = np.asarray(payload["mask"], bool)
                    dense = (np.asarray(payload["dense"], np.float32)
                             if payload.get("dense") is not None
                             else None)
                    scores = srv.predict(ids, mask, dense)
                    self._send(200,
                               {"scores": np.asarray(scores).tolist()})
                except Exception as e:   # noqa: BLE001 — a scoring
                    # failure is the CALLER's named error, never a
                    # silent connection drop
                    self._send(500, {"error": repr(e)})
            elif self.path.startswith("/promote"):
                self._send(200, {"promoted": srv.promote_candidate()})
            else:
                self._send(404, {"error": "not found"})

        def log_message(self, *a):     # quiet: telemetry is the log
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
    port = httpd.server_address[1]
    # the port file is the spawn handshake: committed atomically so the
    # supervisor can never read a torn write
    with ckpt_lib.atomic_file(args.port_file) as tmp:
        with open(tmp, "w") as f:
            json.dump({"port": port, "pid": os.getpid()}, f)
    mon_ctx.spawn(httpd.serve_forever, name="replica-endpoint").start()
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        srv.stop()
    return 0


def _stage_once(args) -> int:
    """Internal entrypoint (lease kill matrix): materialize ONE artifact
    through the shared cache and print the result."""
    cache = SharedStagingCache(args.staging_root,
                               lease_ttl_s=args.lease_ttl_s)
    local = cache.materialize(args.stage)
    print(json.dumps({"local": local, **cache.stats()}), flush=True)
    return 0


def main(argv: list[str] | None = None) -> int:
    """Runbook entrypoint (README "Serving fleet runbook"):
    ``python -m paddlebox_tpu.serving.fleet ROOT --replicas N``."""
    import argparse
    ap = argparse.ArgumentParser(
        description="Supervise N serving replicas off one donefile: "
                    "shared verified staging, crash restart with "
                    "backoff, crash-loop quarantine")
    ap.add_argument("root", help="serving root (local dir or hdfs:// URI)")
    ap.add_argument("--replicas", type=int, default=None,
                    help="replica count (default: "
                         "flags.serving_fleet_replicas)")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--staging-root", default=None)
    ap.add_argument("--poll-s", type=float, default=1.0)
    ap.add_argument("--lease-ttl-s", type=float, default=30.0)
    ap.add_argument("--serve-replica", action="store_true",
                    help=argparse.SUPPRESS)   # internal entrypoints
    ap.add_argument("--port-file", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--stage", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.staging_root is None:
        args.staging_root = os.path.join(
            args.workdir or "fleet_work", "staging")
    if args.stage is not None:
        # argparse reuses `root` as the staging positional's sibling:
        # --stage PATH materializes PATH, `root` is ignored
        return _stage_once(args)
    if args.serve_replica:
        if not args.port_file:
            ap.error("--serve-replica requires --port-file")
        return _serve_replica(args)
    fleet = ReplicaFleet(args.root, replicas=args.replicas,
                         workdir=args.workdir,
                         staging_root=args.staging_root,
                         poll_s=args.poll_s).start()
    print(f"fleet of {fleet.n} replicas on {args.root}; workdir "
          f"{fleet.workdir}", flush=True)
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        fleet.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
