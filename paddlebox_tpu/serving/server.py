"""ServingServer — donefile-tailing, CRC-verifying, hot-swapping scorer.

The serve half of the reference's online loop: ad-serving hosts watch the
xbox donefile, download each announced base/delta, and swap the new model
in while traffic flows (SURVEY.md; the minutes-scale train→serve latency
PAPER.md advertises). The crash-safety contract mirrors the training side:

- **Verify before build.** Every fetched version re-hashes against its
  manifest (serving/artifact.py) — bytes that fail CRC never reach a
  table. With the publisher's announce-after-verify discipline this
  closes the loop: a torn publish is never announced, and even an
  announced artifact later corrupted in storage is diagnosed, not served.
- **Swap without a pause.** The next version's ServingTable + Predictor
  build OFF the request path (the poll thread); the swap itself is one
  atomic rebind of the versioned handle (``self._active``). In-flight
  requests finish on the handle they grabbed; new requests see the new
  version. Zero requests dropped, zero blocked — proven under concurrent
  load by tests/test_serving.py.
- **Degrade, don't die.** A version that fails to download (bounded retry
  + exponential backoff) or verify is skipped with a named diagnostic;
  deltas whose parent was skipped wait for the next base; when nothing
  new can be loaded the server keeps serving the last good version and
  reports staleness (pass lag + age) through the telemetry hub and the
  health endpoint.

Hot keys flagged by the publisher pin into a ReplicaCache
(full-precision — the GpuReplicaCache role, box_wrapper.h:140-248),
refreshed copy-on-write at every swap so the cache can never be observed
mid-update.
"""

from __future__ import annotations

import http.server
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Any

import numpy as np

from paddlebox_tpu import monitor
from paddlebox_tpu.embedding.gating import GateSpec
from paddlebox_tpu.monitor import context as mon_ctx
from paddlebox_tpu.monitor import trace as trace_lib
from paddlebox_tpu.embedding.replica_cache import ReplicaCache
from paddlebox_tpu.fleet.fleet_util import FleetUtil
from paddlebox_tpu.inference import export as export_lib
from paddlebox_tpu.inference.predictor import Predictor
from paddlebox_tpu.inference.serving_table import ServingTable
from paddlebox_tpu.serving import artifact as art
from paddlebox_tpu.serving.publisher import DONEFILE
from paddlebox_tpu.utils import checkpoint as ckpt_lib
from paddlebox_tpu.utils import fs as fs_lib
from paddlebox_tpu.utils.checkpoint import CheckpointCorruptError


def _entry_int(entry: dict | None, key: str) -> int | None:
    """An int field off a donefile entry, None when absent/unparseable."""
    if entry is None:
        return None
    try:
        return int(entry[key])
    except (KeyError, TypeError, ValueError):
        return None


class ServingUnavailableError(RuntimeError):
    """No model version has been loaded yet (empty donefile, or every
    announced version failed verification)."""


class ServingModel:
    """One immutable loaded version — the handle a request grabs once.
    Everything a request touches hangs off this object, so an atomic
    rebind of ``server._active`` IS the swap."""

    __slots__ = ("version", "pass_id", "kind", "predictor", "table",
                 "replica_cache", "hot_keys", "published_ts", "loaded_ts")

    def __init__(self, version: int, pass_id: int, kind: str,
                 predictor: Predictor, table: ServingTable,
                 replica_cache: ReplicaCache | None,
                 hot_keys: np.ndarray, published_ts: int):
        self.version = version
        self.pass_id = pass_id
        self.kind = kind
        self.predictor = predictor
        self.table = table
        self.replica_cache = replica_cache
        self.hot_keys = hot_keys
        self.published_ts = published_ts
        self.loaded_ts = time.time()


class ServingServer:
    """Tails one serving root's donefile and serves the newest verified
    version. Use :meth:`poll_once` for test-driven stepping or
    :meth:`start` for the background tailer; score through
    :meth:`predict` / :meth:`predict_batch` (or a
    serving.frontend.BatchingFrontend on top)."""

    def __init__(self, root: str, *, poll_s: float = 1.0,
                 staging_dir: str | None = None,
                 fetch_attempts: int = 3, fetch_backoff_s: float = 0.25,
                 stale_pass_lag: int = 2, stale_after_s: float = 600.0,
                 health_port: int | None = None):
        self._remote = fs_lib.is_remote(root)
        self.root = root if self._remote else fs_lib.resolve(root)[1]
        self._fs = fs_lib.resolve(root)[0]
        self._fleet = FleetUtil(root)   # donefile discovery (torn-line safe)
        self.poll_s = float(poll_s)
        self._staging = staging_dir
        self.fetch_attempts = max(1, int(fetch_attempts))
        self.fetch_backoff_s = float(fetch_backoff_s)
        self.stale_pass_lag = int(stale_pass_lag)
        self.stale_after_s = float(stale_after_s)
        self._active: ServingModel | None = None
        self._latest_announced: dict | None = None
        self._skipped: dict[int, str] = {}     # version → diagnosis
        self._unusable: set[str] = set()       # entries diagnosed once
        self._swaps = 0
        self._served = 0
        self._request_failures = 0
        self._last_error: str | None = None
        self._last_swap_pause_ms = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._http: Any = None
        self.health_port: int | None = None
        if health_port is not None:
            self._start_health_endpoint(int(health_port))

    # ---- discovery + swap ------------------------------------------------

    @property
    def active(self) -> ServingModel | None:
        return self._active

    def poll_once(self) -> int:
        """One tail step: read the donefile, fetch/verify/build every
        version newer than the active one IN ORDER, swap each in. Returns
        the number of versions applied. Never raises on a bad version —
        it diagnoses, skips, and keeps the last good model serving."""
        trace_lib.ensure_service("serving")   # driver-polled servers too
        entries = self._fleet._entries(DONEFILE)
        if entries:
            self._latest_announced = entries[-1]
        if self._active is None and entries:
            # cold start: the donefile holds the job's whole history, but
            # the newest loadable base + its trailing deltas fully
            # determine the serving state — seek instead of replaying
            # every version. Bases newest-first so a rotted newest base
            # falls back to the previous one; entries before the oldest
            # base are deltas with no loadable root and can never apply.
            base_idx = [i for i, e in enumerate(entries)
                        if str(e.get("kind", "")) == "base"]
            applied = 0
            for i in reversed(base_idx):
                applied += self._apply_entries(entries[i:])
                if self._active is not None:
                    break
            if not base_idx:
                applied = self._apply_entries(entries)
        else:
            applied = self._apply_entries(entries)
        self._update_staleness_gauges()
        return applied

    def _apply_entries(self, entries: list[dict]) -> int:
        active_v = self._active.version if self._active else 0
        applied = 0
        for e in entries:
            try:
                version = int(e["version"])
                kind = str(e["kind"])
                path = str(e["path"])
            except (KeyError, TypeError, ValueError) as err:
                # versionless, so _skipped can't remember it — dedupe on
                # the entry itself or every poll re-diagnoses the same
                # foreign line forever (counter spam drowns the alert,
                # and _last_error masks newer real errors)
                seen = repr(sorted(e.items())) if isinstance(e, dict) \
                    else repr(e)
                if seen not in self._unusable:
                    self._unusable.add(seen)
                    self._diag(-1, f"unusable donefile entry {e!r}: {err}")
                continue
            if version <= active_v or version in self._skipped:
                continue
            if kind == "delta":
                parent = e.get("parent")
                if self._active is None or parent is None \
                        or int(parent) != self._active.version:
                    # parent skipped/never loaded: this delta can never
                    # apply — wait for the next base to resync
                    self._diag(version,
                               f"delta v{version} parents "
                               f"v{parent}, active is "
                               f"v{self._active.version if self._active else None}"
                               f" — waiting for the next base")
                    continue
            staged = None
            try:
                loaded, staged = self._fetch(path)
                model = self._build(loaded, e)
            except Exception as err:   # noqa: BLE001 — keep serving
                self._diag(version, f"{kind} v{version} at {path}: "
                                    f"{err!r}")
                continue
            finally:
                # the build consumed the staged download (arrays are in
                # memory, dense_file loaded) — a long-running remote
                # tailer must not accumulate one artifact per publish
                # until the staging disk fills
                if staged is not None:
                    shutil.rmtree(staged, ignore_errors=True)
            t_swap = time.perf_counter()
            self._active = model           # THE swap: one atomic rebind
            pause_ms = (time.perf_counter() - t_swap) * 1e3
            self._last_swap_pause_ms = pause_ms
            self._swaps += 1
            applied += 1
            active_v = version
            monitor.counter_add("serving.swaps")
            monitor.gauge_set("serving.active_version", version)
            # world trace: the swap is the dst of the publish flow edge
            # — keyed by version (both sides derive it independently),
            # ACTIVATED by the trace context the donefile entry carries
            # (cross-process propagation: the producing run traced this
            # version, so the swap point emits even when this process
            # has no local trace scope) with the publisher's span ids
            # as the explicit parent link
            parent_trace = e.get("trace") if isinstance(
                e.get("trace"), dict) else None
            trace_lib.flow_propagated(
                "publish", f"v{version}", "dst", parent_trace,
                swap_pause_ms=round(pause_ms, 3))
            monitor.event("serving_swap", type="lifecycle",
                          version=version, kind=kind,
                          pass_id=model.pass_id,
                          swap_pause_ms=round(pause_ms, 3),
                          keys=len(model.table))
        return applied

    def _diag(self, version: int, msg: str) -> None:
        self._last_error = msg
        if version >= 0:
            self._skipped[version] = msg
        monitor.counter_add("serving.version_fallbacks")
        monitor.event("serving_version_fallback", version=version,
                      error=msg[:300])
        import warnings
        warnings.warn(f"serving: {msg}; continuing on the last good "
                      f"version")

    def _fetch(self, path: str) -> tuple[dict, str | None]:
        """Local view of one artifact, CRC-verified, plus the staging-dir
        copy to remove once consumed (None when read in place). Remote
        fetches get ``fetch_attempts`` tries with exponential backoff;
        the partial download is removed before each retry and on
        exhaustion."""
        if not self._remote and os.path.isdir(path):
            return art.read_artifact(path, verify=True), None
        if self._staging is None:
            # per-instance: two servers on one host (different roots)
            # staging the same version basename into a shared fixed dir
            # would clobber each other's download mid-read
            self._staging = tempfile.mkdtemp(prefix="pbtpu_serving_stage_")
        stage = self._staging
        os.makedirs(stage, exist_ok=True)
        local = os.path.join(stage, os.path.basename(path.rstrip("/")))
        backoff = self.fetch_backoff_s
        last: Exception | None = None
        for attempt in range(self.fetch_attempts):
            if attempt:
                time.sleep(backoff)
                backoff *= 2.0
                monitor.counter_add("serving.fetch_retries")
            shutil.rmtree(local, ignore_errors=True)
            try:
                self._fs.get(path, local)
                out = art.read_artifact(local, verify=True)
                return out, local
            except (RuntimeError, OSError, ValueError,
                    CheckpointCorruptError) as err:
                last = err
        shutil.rmtree(local, ignore_errors=True)
        raise RuntimeError(
            f"artifact {path} failed to fetch/verify after "
            f"{self.fetch_attempts} attempts: {last}") from last

    def _build(self, loaded: dict, entry: dict) -> ServingModel:
        """Assemble the next ServingModel OFF the request path. Base →
        fresh table (+ predictor; the jitted forward is reused across
        versions of the same model config, so a swap never recompiles);
        delta → copy-on-write merge into a copy of the active table."""
        t0 = time.perf_counter()
        meta = loaded["meta"]
        if int(meta["version"]) != int(entry["version"]):
            # CRCs only prove the artifact matches ITS manifest — a
            # misdirected fetch (stale staging, wrong path in a foreign
            # donefile line) verifies clean while being another version's
            # model entirely
            raise CheckpointCorruptError(
                str(entry.get("path", "?")),
                f"artifact claims v{meta['version']} != announced "
                f"v{entry['version']}")
        kind = meta["kind"]
        mm = loaded["model_meta"]
        if kind == "base":
            g = meta.get("gate")
            gate = (GateSpec(int(g[0]), int(g[1]), float(g[2]),
                             float(g[3])) if g else None)
            table = ServingTable(loaded["keys"], loaded["vals"], gate=gate)
            hot_keys = np.asarray(loaded["keys"])[
                np.asarray(loaded["hot"], bool)].astype(np.uint64)
        else:
            active = self._active
            table = active.table.copy()
            table._merge(loaded["keys"], loaded["rows"])
            if len(loaded["removed"]):
                table._drop(loaded["removed"])
            hot_keys = active.hot_keys
        predictor = self._make_predictor(mm, loaded["dense_file"], table)
        cache = self._build_replica_cache(table, hot_keys)
        monitor.counter_add("serving.build_seconds",
                            time.perf_counter() - t0)
        return ServingModel(int(meta["version"]), int(meta["pass_id"]),
                            kind, predictor, table, cache, hot_keys,
                            int(entry.get("ts", meta.get("ts", 0))))

    def _make_predictor(self, model_meta: dict, dense_file: str,
                        table: ServingTable) -> Predictor:
        import jax
        from paddlebox_tpu.models import MODEL_REGISTRY
        from paddlebox_tpu.utils import checkpoint as _ckpt
        active = self._active
        if active is not None and \
                active.predictor.model.name == model_meta["model"] and \
                _normalize_cfg(export_lib.model_config(
                    active.predictor.model)) \
                == _normalize_cfg(model_meta["config"]):
            template = active.predictor.params
            params = _ckpt.load_pytree(template, dense_file)
            # same architecture: share the compiled forward across the swap
            return active.predictor.with_model(params, table)
        cfg = _normalize_cfg(model_meta["config"])
        import jax.numpy as jnp
        if "compute_dtype" in cfg:
            cfg = dict(cfg, compute_dtype=jnp.dtype(cfg["compute_dtype"]))
        model = MODEL_REGISTRY[model_meta["model"]](**cfg)
        template = model.init(jax.random.PRNGKey(0))
        params = _ckpt.load_pytree(template, dense_file)
        schema = export_lib._schema_from_json(model_meta["schema"])
        return Predictor(model, params, table, schema,
                         label_slot=model_meta.get("label_slot", "label"))

    def _build_replica_cache(self, table: ServingTable,
                             hot_keys: np.ndarray) -> ReplicaCache | None:
        """Copy-on-write hot tier: a fresh cache per version, built from
        the NEW table's rows for the flagged keys (keys evicted since the
        flagging base simply drop out). The active version's cache is
        never mutated — a device holding the old HBM mirror keeps it
        consistent until it uploads the new one."""
        if not len(hot_keys) or not len(table):
            return None
        pos, hit = table._probe(np.asarray(hot_keys, np.uint64))
        live = np.asarray(hot_keys, np.uint64)[hit]
        if not len(live):
            return None
        return ReplicaCache.from_keys_rows(live, table.vals[pos[hit]])

    # ---- request path ----------------------------------------------------

    def _handle(self) -> ServingModel:
        m = self._active
        if m is None:
            raise ServingUnavailableError(
                f"no serving model loaded from {self.root} yet "
                f"(last error: {self._last_error})")
        return m

    def predict(self, ids: np.ndarray, mask: np.ndarray,
                dense: np.ndarray | None = None) -> np.ndarray:
        m = self._handle()
        try:
            out = m.predictor.predict(ids, mask, dense)
        except Exception:
            self._request_failures += 1
            monitor.counter_add("serving.request_failures")
            raise
        self._served += len(np.asarray(ids))
        return out

    def predict_batch(self, pb) -> np.ndarray:
        m = self._handle()
        try:
            out = m.predictor.predict_batch(pb)
        except Exception:
            self._request_failures += 1
            monitor.counter_add("serving.request_failures")
            raise
        self._served += int(pb.num)
        return out

    # ---- staleness / health ----------------------------------------------

    def _update_staleness_gauges(self) -> None:
        h = self.health()
        if h["pass_lag"] is not None:
            monitor.gauge_set("serving.pass_lag", h["pass_lag"])
        if h["age_seconds"] is not None:
            monitor.gauge_set("serving.staleness_seconds",
                              h["age_seconds"])

    def health(self) -> dict:
        """The health endpoint's payload: what is serving, how stale it
        is, and whether the tail is degraded (newer versions announced
        but unloadable). ``status``: ok | stale | degraded | empty."""
        m = self._active
        ann = self._latest_announced
        # snapshot: the tailer thread inserts concurrently, and iterating
        # the live dict from the HTTP thread can raise "changed size
        # during iteration" exactly when versions are being skipped
        skipped = list(self._skipped)
        # the tail entry is whatever parses off the donefile — a foreign
        # or hand-written last line must degrade the report, not 500 it
        ann_v = _entry_int(ann, "version")
        ann_pass = _entry_int(ann, "pass")
        if m is None:
            status = "empty"
            pass_lag = ann_pass if ann_pass is not None else None
            age = None
        else:
            pass_lag = (max(0, ann_pass - m.pass_id)
                        if ann_pass is not None else 0)
            age = time.time() - (m.published_ts or m.loaded_ts)
            if ann_v is not None and ann_v > m.version \
                    and any(v > m.version for v in skipped):
                status = "degraded"
            elif pass_lag > self.stale_pass_lag \
                    or age > self.stale_after_s:
                status = "stale"
            else:
                status = "ok"
        return {"status": status,
                "active_version": m.version if m else None,
                "active_pass": m.pass_id if m else None,
                "active_kind": m.kind if m else None,
                "table_keys": len(m.table) if m else 0,
                "hot_cached_keys": (len(m.replica_cache) - 1
                                    if m and m.replica_cache else 0),
                "announced_version": ann_v,
                "announced_pass": ann_pass,
                "pass_lag": pass_lag,
                "age_seconds": None if age is None else round(age, 1),
                "swaps": self._swaps,
                "last_swap_pause_ms": round(self._last_swap_pause_ms, 3),
                "served": self._served,
                "request_failures": self._request_failures,
                "skipped_versions": sorted(skipped),
                "last_error": self._last_error}

    # ---- background tailer ----------------------------------------------

    def start(self) -> "ServingServer":
        """Background donefile tailer: poll every ``poll_s`` seconds. A
        poll that raises (remote-FS outage past the retry budget) is
        recorded and the loop continues — the server's job under failure
        is to keep serving what it has."""
        if self._thread is not None:
            return self
        # pass-less process: with flags.trace on, open a standing trace
        # scope so swap records/flow points are stamped and mergeable
        # against the training ranks' streams
        trace_lib.ensure_service("serving")
        self._stop.clear()

        def _run():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception as e:   # noqa: BLE001
                    self._last_error = f"poll failed: {e!r}"
                    monitor.counter_add("serving.poll_failures")
                self._stop.wait(self.poll_s)

        self._thread = mon_ctx.spawn(_run, name="serving-tailer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self._http is not None:
            self._http.shutdown()
            self._http = None

    # ---- health endpoint -------------------------------------------------

    def _start_health_endpoint(self, port: int) -> None:
        """Tiny stdlib HTTP endpoint: ``/healthz`` returns the health()
        JSON (200 while a model serves, 503 before the first load),
        ``/metrics`` the telemetry hub's Prometheus exposition — the
        operator surface the runbook (README) curls."""
        server = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API)
                if self.path.startswith("/healthz"):
                    body = json.dumps(server.health()).encode()
                    code = 503 if server._active is None else 200
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    body = monitor.hub().prometheus_text().encode()
                    code, ctype = 200, "text/plain; version=0.0.4"
                else:
                    body, code, ctype = b"not found", 404, "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # quiet: telemetry is the log
                pass

        self._http = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                                     _Handler)
        self.health_port = self._http.server_address[1]
        mon_ctx.spawn(self._http.serve_forever,
                      name="serving-health").start()


def _normalize_cfg(cfg: dict) -> dict:
    return {k: (tuple(v) if isinstance(v, list) else v)
            for k, v in cfg.items()}


def main(argv: list[str] | None = None) -> int:
    """Runbook entrypoint (README "Serving runbook"):
    ``python -m paddlebox_tpu.serving.server ROOT [--health-port N]``
    tails ROOT's donefile forever, hot-swapping each announced version
    and serving /healthz + /metrics."""
    import argparse
    ap = argparse.ArgumentParser(
        description="Serve the newest verified model published to ROOT "
                    "(tails serving_model.donefile; hot-swaps new "
                    "versions under load; degrades to the last good "
                    "version when publishes stop or verification fails)")
    ap.add_argument("root", help="serving root (local dir or hdfs:// URI)")
    ap.add_argument("--poll-s", type=float, default=1.0)
    ap.add_argument("--health-port", type=int, default=8080,
                    help="0 picks a free port; printed on startup")
    ap.add_argument("--staging-dir", default=None,
                    help="where remote artifacts download before verify")
    ap.add_argument("--stale-pass-lag", type=int, default=2)
    ap.add_argument("--stale-after-s", type=float, default=600.0)
    args = ap.parse_args(argv)
    srv = ServingServer(args.root, poll_s=args.poll_s,
                        staging_dir=args.staging_dir,
                        stale_pass_lag=args.stale_pass_lag,
                        stale_after_s=args.stale_after_s,
                        health_port=args.health_port).start()
    print(f"serving {args.root}; health at "
          f"http://127.0.0.1:{srv.health_port}/healthz", flush=True)
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
