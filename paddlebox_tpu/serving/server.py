"""ServingServer — donefile-tailing, CRC-verifying, hot-swapping scorer.

The serve half of the reference's online loop: ad-serving hosts watch the
xbox donefile, download each announced base/delta, and swap the new model
in while traffic flows (SURVEY.md; the minutes-scale train→serve latency
PAPER.md advertises). The crash-safety contract mirrors the training side:

- **Verify before build.** Every fetched version re-hashes against its
  manifest (serving/artifact.py) — bytes that fail CRC never reach a
  table. With the publisher's announce-after-verify discipline this
  closes the loop: a torn publish is never announced, and even an
  announced artifact later corrupted in storage is diagnosed, not served.
- **Swap without a pause.** The next version's ServingTable + Predictor
  build OFF the request path (the poll thread); the swap itself is one
  atomic rebind of the versioned handle (``self._active``). In-flight
  requests finish on the handle they grabbed; new requests see the new
  version. Zero requests dropped, zero blocked — proven under concurrent
  load by tests/test_serving.py.
- **Degrade, don't die.** A version that fails to download (bounded retry
  + exponential backoff) or verify is skipped with a named diagnostic;
  deltas whose parent was skipped wait for the next base; when nothing
  new can be loaded the server keeps serving the last good version and
  reports staleness (pass lag + age) through the telemetry hub and the
  health endpoint.

Hot keys flagged by the publisher pin into a ReplicaCache
(full-precision — the GpuReplicaCache role, box_wrapper.h:140-248),
refreshed copy-on-write at every swap so the cache can never be observed
mid-update.
"""

from __future__ import annotations

import contextlib
import http.server
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Any

import numpy as np

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags
from paddlebox_tpu.embedding.gating import GateSpec
from paddlebox_tpu.monitor import context as mon_ctx
from paddlebox_tpu.monitor import trace as trace_lib
from paddlebox_tpu.embedding.replica_cache import ReplicaCache
from paddlebox_tpu.fleet.fleet_util import FleetUtil
from paddlebox_tpu.inference import export as export_lib
from paddlebox_tpu.inference.predictor import Predictor
from paddlebox_tpu.inference.serving_table import ServingTable
from paddlebox_tpu.serving import artifact as art
from paddlebox_tpu.serving.obs import ServingObs
from paddlebox_tpu.serving.publisher import DONEFILE
from paddlebox_tpu.utils import checkpoint as ckpt_lib
from paddlebox_tpu.utils import fs as fs_lib
from paddlebox_tpu.utils.checkpoint import CheckpointCorruptError


def _entry_int(entry: dict | None, key: str) -> int | None:
    """An int field off a donefile entry, None when absent/unparseable."""
    if entry is None:
        return None
    try:
        return int(entry[key])
    except (KeyError, TypeError, ValueError):
        return None


class ServingUnavailableError(RuntimeError):
    """No model version has been loaded yet (empty donefile, or every
    announced version failed verification)."""


class ServingModel:
    """One immutable loaded version — the handle a request grabs once.
    Everything a request touches hangs off this object, so an atomic
    rebind of ``server._active`` IS the swap."""

    __slots__ = ("version", "pass_id", "kind", "predictor", "table",
                 "replica_cache", "hot_keys", "published_ts", "loaded_ts",
                 "trace")

    def __init__(self, version: int, pass_id: int, kind: str,
                 predictor: Predictor, table: ServingTable,
                 replica_cache: ReplicaCache | None,
                 hot_keys: np.ndarray, published_ts: int,
                 trace: dict | None = None):
        self.version = version
        self.pass_id = pass_id
        self.kind = kind
        self.predictor = predictor
        self.table = table
        self.replica_cache = replica_cache
        self.hot_keys = hot_keys
        self.published_ts = published_ts
        self.loaded_ts = time.time()
        # the producing run's {"trace_id", "span_id"} off the donefile
        # entry — request spans scored on this version parent-link to
        # its publish span through these (ISSUE 19)
        self.trace = trace


class ServingServer:
    """Tails one serving root's donefile and serves the newest verified
    version. Use :meth:`poll_once` for test-driven stepping or
    :meth:`start` for the background tailer; score through
    :meth:`predict` / :meth:`predict_batch` (or a
    serving.frontend.BatchingFrontend on top)."""

    def __init__(self, root: str, *, poll_s: float = 1.0,
                 staging_dir: str | None = None,
                 fetch_attempts: int = 3, fetch_backoff_s: float = 0.25,
                 stale_pass_lag: int = 2, stale_after_s: float = 600.0,
                 health_port: int | None = None,
                 staging_cache=None):
        self._remote = fs_lib.is_remote(root)
        self.root = root if self._remote else fs_lib.resolve(root)[1]
        self._fs = fs_lib.resolve(root)[0]
        # fleet mode (serving/fleet.py): replicas on one host share ONE
        # download+verify per version through this cache instead of each
        # staging its own copy
        self._staging_cache = staging_cache
        self._fleet = FleetUtil(root)   # donefile discovery (torn-line safe)
        self.poll_s = float(poll_s)
        self._staging = staging_dir
        self.fetch_attempts = max(1, int(fetch_attempts))
        self.fetch_backoff_s = float(fetch_backoff_s)
        self.stale_pass_lag = int(stale_pass_lag)
        self.stale_after_s = float(stale_after_s)
        self._active: ServingModel | None = None
        # version-split / shadow (ISSUE 19): with the split flags on, a
        # newly built version lands HERE while _active keeps serving —
        # stable + candidate score side by side until promotion
        self._candidate: ServingModel | None = None
        self._latest_announced: dict | None = None
        self._skipped: dict[int, str] = {}     # version → diagnosis
        self._unusable: set[str] = set()       # entries diagnosed once
        self._swaps = 0
        self._served = 0
        self._request_failures = 0
        self._last_error: str | None = None
        self._last_swap_pause_ms = 0.0
        # serving observability: per-window/per-version bookkeeping,
        # built on first use so flag flips after construction stick
        self._obs: ServingObs | None = None
        self._obs_lock = threading.Lock()
        self._split_acc = 0.0                  # deterministic router
        self._score_n = 0                      # serve/score sampling
        self._win_failures0 = 0                # counters at last commit
        self._win_swaps0 = 0
        self._building = False                 # a version is rebuilding
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._http: Any = None
        self.health_port: int | None = None
        if health_port is not None:
            self._start_health_endpoint(int(health_port))

    # ---- discovery + swap ------------------------------------------------

    @property
    def active(self) -> ServingModel | None:
        return self._active

    @property
    def candidate(self) -> ServingModel | None:
        return self._candidate

    def _newest(self) -> ServingModel | None:
        """The newest loaded model — the candidate when one is held,
        else the active. Donefile progress and version lag are measured
        against it."""
        return self._candidate or self._active

    @staticmethod
    def _split_on() -> bool:
        return (float(flags.serving_split_fraction) > 0.0
                or bool(flags.serving_shadow))

    def poll_once(self) -> int:
        """One tail step: read the donefile, fetch/verify/build every
        version newer than the active one IN ORDER, swap each in. Returns
        the number of versions applied. Never raises on a bad version —
        it diagnoses, skips, and keeps the last good model serving."""
        trace_lib.ensure_service("serving")   # driver-polled servers too
        entries = self._fleet._entries(DONEFILE)
        if entries:
            self._latest_announced = entries[-1]
        if self._active is None and entries:
            # cold start: the donefile holds the job's whole history, but
            # the newest loadable base + its trailing deltas fully
            # determine the serving state — seek instead of replaying
            # every version. Bases newest-first so a rotted newest base
            # falls back to the previous one; entries before the oldest
            # base are deltas with no loadable root and can never apply.
            base_idx = [i for i, e in enumerate(entries)
                        if str(e.get("kind", "")) == "base"]
            applied = 0
            for i in reversed(base_idx):
                applied += self._apply_entries(entries[i:])
                if self._active is not None:
                    break
            if not base_idx:
                applied = self._apply_entries(entries)
        else:
            applied = self._apply_entries(entries)
        # split flags turned off while a candidate is held: promote it —
        # the newest version must not strand behind a dead A/B
        if self._candidate is not None and not self._split_on():
            self.promote_candidate()
        self._update_staleness_gauges()
        self.commit_window()                  # due-gated; no-op early
        return applied

    def _apply_entries(self, entries: list[dict]) -> int:
        newest = self._newest()
        active_v = newest.version if newest else 0
        applied = 0
        for e in entries:
            try:
                version = int(e["version"])
                kind = str(e["kind"])
                path = str(e["path"])
            except (KeyError, TypeError, ValueError) as err:
                # versionless, so _skipped can't remember it — dedupe on
                # the entry itself or every poll re-diagnoses the same
                # foreign line forever (counter spam drowns the alert,
                # and _last_error masks newer real errors)
                seen = repr(sorted(e.items())) if isinstance(e, dict) \
                    else repr(e)
                if seen not in self._unusable:
                    self._unusable.add(seen)
                    self._diag(-1, f"unusable donefile entry {e!r}: {err}")
                continue
            if version <= active_v or version in self._skipped:
                continue
            if kind == "delta":
                parent = e.get("parent")
                base = self._newest()      # deltas chain off the newest
                if base is None or parent is None \
                        or int(parent) != base.version:
                    # parent skipped/never loaded: this delta can never
                    # apply — wait for the next base to resync
                    self._diag(version,
                               f"delta v{version} parents "
                               f"v{parent}, active is "
                               f"v{base.version if base else None}"
                               f" — waiting for the next base")
                    continue
            staged = None
            self._building = True      # swap-aware draining: a fleet
            try:                       # router pulls a rebuilding
                # replica out of rotation off this health() bit
                loaded, staged = self._fetch(path)
                model = self._build(loaded, e)
            except Exception as err:   # noqa: BLE001 — keep serving
                self._diag(version, f"{kind} v{version} at {path}: "
                                    f"{err!r}")
                continue
            finally:
                self._building = False
                # the build consumed the staged download (arrays are in
                # memory, dense_file loaded) — a long-running remote
                # tailer must not accumulate one artifact per publish
                # until the staging disk fills
                if staged is not None:
                    shutil.rmtree(staged, ignore_errors=True)
            # the producing run's trace context off the donefile entry:
            # the swap flow point AND every request span scored on this
            # version parent-link through it (cross-process propagation)
            parent_trace = e.get("trace") if isinstance(
                e.get("trace"), dict) else None
            model.trace = parent_trace
            t_swap = time.perf_counter()
            if self._split_on() and self._active is not None:
                # version-split/shadow: the new version lands as the
                # CANDIDATE; stable keeps serving until promotion
                prev, self._candidate = self._candidate, model
                role = "candidate"
                pause_ms = (time.perf_counter() - t_swap) * 1e3
                monitor.counter_add("serving.candidate_loads")
                monitor.gauge_set("serving.candidate_version", version)
                with self._obs_lock:
                    if prev is not None:
                        self._obs_get().drop_version(prev.version)
                    self._obs_get().ensure_version(version, "candidate")
            else:
                self._active = model       # THE swap: one atomic rebind
                role = "stable"
                pause_ms = (time.perf_counter() - t_swap) * 1e3
                self._last_swap_pause_ms = pause_ms
                self._swaps += 1
                monitor.counter_add("serving.swaps")
                monitor.gauge_set("serving.active_version", version)
            applied += 1
            active_v = version
            # world trace: the swap is the dst of the publish flow edge
            # — keyed by version (both sides derive it independently),
            # ACTIVATED by the trace context the donefile entry carries
            # (cross-process propagation: the producing run traced this
            # version, so the swap point emits even when this process
            # has no local trace scope) with the publisher's span ids
            # as the explicit parent link
            trace_lib.flow_propagated(
                "publish", f"v{version}", "dst", parent_trace,
                swap_pause_ms=round(pause_ms, 3))
            monitor.event("serving_swap", type="lifecycle",
                          version=version, kind=kind, role=role,
                          pass_id=model.pass_id,
                          swap_pause_ms=round(pause_ms, 3),
                          keys=len(model.table))
        return applied

    def _diag(self, version: int, msg: str) -> None:
        self._last_error = msg
        if version >= 0:
            self._skipped[version] = msg
        monitor.counter_add("serving.version_fallbacks")
        monitor.event("serving_version_fallback", version=version,
                      error=msg[:300])
        import warnings
        warnings.warn(f"serving: {msg}; continuing on the last good "
                      f"version")

    def _fetch(self, path: str) -> tuple[dict, str | None]:
        """Local view of one artifact, CRC-verified, plus the staging-dir
        copy to remove once consumed (None when read in place). Remote
        fetches get ``fetch_attempts`` tries with exponential backoff;
        the partial download is removed before each retry and on
        exhaustion."""
        if self._staging_cache is not None:
            # fleet replicas: one lease-guarded download+CRC-verify per
            # version per HOST (serving/fleet.py SharedStagingCache);
            # the materialized copy was verified under the lease, so the
            # per-replica re-verify is intentionally skipped — that one
            # verify IS the host's verification budget. The shared copy
            # outlives this build (other replicas read it): staged=None.
            local = self._staging_cache.materialize(path)
            return art.read_artifact(local, verify=False), None
        if not self._remote and os.path.isdir(path):
            return art.read_artifact(path, verify=True), None
        if self._staging is None:
            # per-instance: two servers on one host (different roots)
            # staging the same version basename into a shared fixed dir
            # would clobber each other's download mid-read
            self._staging = tempfile.mkdtemp(prefix="pbtpu_serving_stage_")
        stage = self._staging
        os.makedirs(stage, exist_ok=True)
        local = os.path.join(stage, os.path.basename(path.rstrip("/")))
        backoff = self.fetch_backoff_s
        last: Exception | None = None
        for attempt in range(self.fetch_attempts):
            if attempt:
                time.sleep(backoff)
                backoff *= 2.0
                monitor.counter_add("serving.fetch_retries")
            shutil.rmtree(local, ignore_errors=True)
            try:
                self._fs.get(path, local)
                out = art.read_artifact(local, verify=True)
                return out, local
            except (RuntimeError, OSError, ValueError,
                    CheckpointCorruptError) as err:
                last = err
        shutil.rmtree(local, ignore_errors=True)
        raise RuntimeError(
            f"artifact {path} failed to fetch/verify after "
            f"{self.fetch_attempts} attempts: {last}") from last

    def _build(self, loaded: dict, entry: dict) -> ServingModel:
        """Assemble the next ServingModel OFF the request path. Base →
        fresh table (+ predictor; the jitted forward is reused across
        versions of the same model config, so a swap never recompiles);
        delta → copy-on-write merge into a copy of the active table."""
        t0 = time.perf_counter()
        meta = loaded["meta"]
        if int(meta["version"]) != int(entry["version"]):
            # CRCs only prove the artifact matches ITS manifest — a
            # misdirected fetch (stale staging, wrong path in a foreign
            # donefile line) verifies clean while being another version's
            # model entirely
            raise CheckpointCorruptError(
                str(entry.get("path", "?")),
                f"artifact claims v{meta['version']} != announced "
                f"v{entry['version']}")
        kind = meta["kind"]
        mm = loaded["model_meta"]
        if kind == "base":
            g = meta.get("gate")
            gate = (GateSpec(int(g[0]), int(g[1]), float(g[2]),
                             float(g[3])) if g else None)
            table = ServingTable(loaded["keys"], loaded["vals"], gate=gate)
            hot_keys = np.asarray(loaded["keys"])[
                np.asarray(loaded["hot"], bool)].astype(np.uint64)
        else:
            active = self._newest()        # deltas chain off the newest
            table = active.table.copy()
            table._merge(loaded["keys"], loaded["rows"])
            if len(loaded["removed"]):
                table._drop(loaded["removed"])
            hot_keys = active.hot_keys
        predictor = self._make_predictor(mm, loaded["dense_file"], table)
        cache = self._build_replica_cache(table, hot_keys)
        monitor.counter_add("serving.build_seconds",
                            time.perf_counter() - t0)
        return ServingModel(int(meta["version"]), int(meta["pass_id"]),
                            kind, predictor, table, cache, hot_keys,
                            int(entry.get("ts", meta.get("ts", 0))))

    def _make_predictor(self, model_meta: dict, dense_file: str,
                        table: ServingTable) -> Predictor:
        import jax
        from paddlebox_tpu.models import MODEL_REGISTRY
        from paddlebox_tpu.utils import checkpoint as _ckpt
        active = self._newest()
        if active is not None and \
                active.predictor.model.name == model_meta["model"] and \
                _normalize_cfg(export_lib.model_config(
                    active.predictor.model)) \
                == _normalize_cfg(model_meta["config"]):
            template = active.predictor.params
            params = _ckpt.load_pytree(template, dense_file)
            # same architecture: share the compiled forward across the swap
            return active.predictor.with_model(params, table)
        cfg = _normalize_cfg(model_meta["config"])
        import jax.numpy as jnp
        if "compute_dtype" in cfg:
            cfg = dict(cfg, compute_dtype=jnp.dtype(cfg["compute_dtype"]))
        model = MODEL_REGISTRY[model_meta["model"]](**cfg)
        template = model.init(jax.random.PRNGKey(0))
        params = _ckpt.load_pytree(template, dense_file)
        schema = export_lib._schema_from_json(model_meta["schema"])
        return Predictor(model, params, table, schema,
                         label_slot=model_meta.get("label_slot", "label"))

    def _build_replica_cache(self, table: ServingTable,
                             hot_keys: np.ndarray) -> ReplicaCache | None:
        """Copy-on-write hot tier: a fresh cache per version, built from
        the NEW table's rows for the flagged keys (keys evicted since the
        flagging base simply drop out). The active version's cache is
        never mutated — a device holding the old HBM mirror keeps it
        consistent until it uploads the new one."""
        if not len(hot_keys) or not len(table):
            return None
        pos, hit = table._probe(np.asarray(hot_keys, np.uint64))
        live = np.asarray(hot_keys, np.uint64)[hit]
        if not len(live):
            return None
        return ReplicaCache.from_keys_rows(live, table.vals[pos[hit]])

    def promote_candidate(self) -> bool:
        """Promote the held candidate to stable (the A/B verdict came
        in, or the split flags went off). Returns whether a promotion
        happened."""
        cand = self._candidate
        if cand is None:
            return False
        old = self._active
        t_swap = time.perf_counter()
        self._active = cand                # THE swap: one atomic rebind
        self._candidate = None
        pause_ms = (time.perf_counter() - t_swap) * 1e3
        self._last_swap_pause_ms = pause_ms
        self._swaps += 1
        monitor.counter_add("serving.swaps")
        monitor.gauge_set("serving.active_version", cand.version)
        with self._obs_lock:
            obs = self._obs_get()
            obs.ensure_version(cand.version, "stable")
            if old is not None:
                obs.drop_version(old.version)
        monitor.event("serving_swap", type="lifecycle",
                      version=cand.version, kind=cand.kind,
                      role="stable", promoted=True,
                      pass_id=cand.pass_id,
                      swap_pause_ms=round(pause_ms, 3),
                      keys=len(cand.table))
        return True

    # ---- request path ----------------------------------------------------

    def _handle(self) -> ServingModel:
        m = self._active
        if m is None:
            raise ServingUnavailableError(
                f"no serving model loaded from {self.root} yet "
                f"(last error: {self._last_error})")
        return m

    def _obs_get(self) -> ServingObs:
        if self._obs is None:
            self._obs = ServingObs()
        return self._obs

    def _score(self, model: ServingModel, ids, mask, dense,
               served: bool) -> np.ndarray:
        """Score one batch on ``model``, with per-version latency/score
        attribution and (every ``flags.serving_trace_sample``-th served
        batch) a ``serve/score`` span parent-linked to the version's
        publish span via the donefile-carried ids."""
        role = "candidate" if model is self._candidate else "stable"
        n = int(flags.serving_trace_sample)
        ctx: Any = contextlib.nullcontext()
        if n > 0 and served:
            self._score_n += 1
            if self._score_n % n == 0:
                span_fields = {"version": model.version, "role": role}
                if isinstance(model.trace, dict):
                    # parent link as FIELDS: the envelope's trace keys
                    # belong to THIS process's scope; the propagated
                    # producer ids ride the payload (the merger draws
                    # the cross-process arrow off them)
                    span_fields["parent_trace_id"] = \
                        model.trace.get("trace_id")
                    span_fields["parent_span_id"] = \
                        model.trace.get("span_id")
                ctx = monitor.span("serve/score", **span_fields)
        t0 = time.perf_counter()
        with ctx:
            out = model.predictor.predict(ids, mask, dense)
        if flags.serving_window_s > 0 or self._split_on():
            with self._obs_lock:
                self._obs_get().record(
                    model.version, role, out,
                    (time.perf_counter() - t0) * 1e3, served)
        return out

    def predict(self, ids: np.ndarray, mask: np.ndarray,
                dense: np.ndarray | None = None) -> np.ndarray:
        m = self._handle()
        cand = self._candidate
        serve_model = m
        if cand is not None and not flags.serving_shadow:
            # deterministic live split: route every 1/fraction-th batch
            # to the candidate (accumulator, not RNG — reproducible)
            frac = float(flags.serving_split_fraction)
            if frac > 0.0:
                with self._obs_lock:
                    self._split_acc += frac
                    if self._split_acc >= 1.0:
                        self._split_acc -= 1.0
                        serve_model = cand
        try:
            out = self._score(serve_model, ids, mask, dense, served=True)
        except Exception:
            self._request_failures += 1
            monitor.counter_add("serving.request_failures")
            raise
        if cand is not None and flags.serving_shadow:
            # shadow: score the candidate too, serve the stable answer;
            # a shadow failure is counted, never surfaced to the caller
            try:
                self._score(cand, ids, mask, dense, served=False)
            except Exception:   # noqa: BLE001 — shadow must not break serving
                monitor.counter_add("serving.shadow_failures")
        self._served += len(np.asarray(ids))
        return out

    def predict_batch(self, pb) -> np.ndarray:
        m = self._handle()
        try:
            out = m.predictor.predict_batch(pb)
        except Exception:
            self._request_failures += 1
            monitor.counter_add("serving.request_failures")
            raise
        self._served += int(pb.num)
        return out

    # ---- delayed labels / window records (ISSUE 19) ----------------------

    def observe_labels(self, labels, *, preds=None,
                       version: int | None = None) -> dict:
        """Delayed labels arrived: join them to the scores the loaded
        versions produced and feed the per-version AUC (the serving half
        of the paper's AUC-runner A/B). See ServingObs.observe_labels.
        Returns {version: joined_count}."""
        with self._obs_lock:
            return self._obs_get().observe_labels(labels,
                                                  version=version,
                                                  preds=preds)

    def commit_window(self, force: bool = False,
                      now: float | None = None) -> dict | None:
        """Commit one serving flight record when the window cadence is
        due (``force`` for test/bench-driven stepping): the fields go
        out as a ``serving_window`` event (``type="serving_record"``,
        schema-checked by monitor/flight.py) and come back to the
        caller. None when not due."""
        obs = self._obs_get()
        if not (force or obs.due(now)):
            return None
        newest = self._newest()
        ann_pass = _entry_int(self._latest_announced, "pass")
        lag = (max(0, ann_pass - newest.pass_id)
               if newest is not None and ann_pass is not None else 0)
        with self._obs_lock:
            fields = obs.commit(
                now,
                failures=int(self._request_failures
                             - self._win_failures0),
                swaps=int(self._swaps - self._win_swaps0),
                version_lag=int(lag),
                active_version=(self._active.version
                                if self._active else None),
                candidate_version=(self._candidate.version
                                   if self._candidate else None),
                replica_hot_keys=(len(newest.replica_cache) - 1
                                  if newest and newest.replica_cache
                                  else 0))
        self._win_failures0 = self._request_failures
        self._win_swaps0 = self._swaps
        monitor.event("serving_window", type="serving_record", **fields)
        return fields

    # ---- staleness / health ----------------------------------------------

    def _update_staleness_gauges(self) -> None:
        h = self.health()
        if h["pass_lag"] is not None:
            monitor.gauge_set("serving.pass_lag", h["pass_lag"])
        if h["age_seconds"] is not None:
            monitor.gauge_set("serving.staleness_seconds",
                              h["age_seconds"])

    def health(self) -> dict:
        """The health endpoint's payload: what is serving, how stale it
        is, and whether the tail is degraded (newer versions announced
        but unloadable). ``status``: ok | stale | degraded | empty."""
        m = self._active
        cand = self._candidate
        newest = cand or m
        ann = self._latest_announced
        # snapshot: the tailer thread inserts concurrently, and iterating
        # the live dict from the HTTP thread can raise "changed size
        # during iteration" exactly when versions are being skipped
        skipped = list(self._skipped)
        # the tail entry is whatever parses off the donefile — a foreign
        # or hand-written last line must degrade the report, not 500 it
        ann_v = _entry_int(ann, "version")
        ann_pass = _entry_int(ann, "pass")
        now = time.time()
        if m is None:
            status = "empty"
            pass_lag = ann_pass if ann_pass is not None else None
            age = None
        else:
            # staleness is measured against the NEWEST loaded model: a
            # fresh candidate means the tail is keeping up even while
            # stable intentionally lags behind the split
            pass_lag = (max(0, ann_pass - newest.pass_id)
                        if ann_pass is not None else 0)
            age = now - (newest.published_ts or newest.loaded_ts)
            if ann_v is not None and ann_v > newest.version \
                    and any(v > newest.version for v in skipped):
                status = "degraded"
            elif pass_lag > self.stale_pass_lag \
                    or age > self.stale_after_s:
                status = "stale"
            else:
                status = "ok"
        # per-version staleness for a fleet health-checker: a
        # half-swapped replica is visible as stable/candidate ids plus
        # each version's own age (ISSUE 19)
        versions = {}
        for vm, role in ((m, "stable"), (cand, "candidate")):
            if vm is None:
                continue
            versions[str(vm.version)] = {
                "role": role, "pass_id": vm.pass_id, "kind": vm.kind,
                "age_seconds": round(
                    now - (vm.published_ts or vm.loaded_ts), 1)}
        return {"status": status,
                "building": self._building,
                "active_version": m.version if m else None,
                "active_pass": m.pass_id if m else None,
                "active_kind": m.kind if m else None,
                "table_keys": len(m.table) if m else 0,
                "hot_cached_keys": (len(m.replica_cache) - 1
                                    if m and m.replica_cache else 0),
                "candidate_version": cand.version if cand else None,
                "candidate_pass": cand.pass_id if cand else None,
                "split_fraction": float(flags.serving_split_fraction),
                "shadow": bool(flags.serving_shadow),
                "versions": versions,
                "announced_version": ann_v,
                "announced_pass": ann_pass,
                "pass_lag": pass_lag,
                "age_seconds": None if age is None else round(age, 1),
                "swaps": self._swaps,
                "last_swap_pause_ms": round(self._last_swap_pause_ms, 3),
                "served": self._served,
                "request_failures": self._request_failures,
                "skipped_versions": sorted(skipped),
                "last_error": self._last_error}

    # ---- background tailer ----------------------------------------------

    def start(self) -> "ServingServer":
        """Background donefile tailer: poll every ``poll_s`` seconds. A
        poll that raises (remote-FS outage past the retry budget) is
        recorded and the loop continues — the server's job under failure
        is to keep serving what it has."""
        if self._thread is not None:
            return self
        # pass-less process: with flags.trace on, open a standing trace
        # scope so swap records/flow points are stamped and mergeable
        # against the training ranks' streams
        trace_lib.ensure_service("serving")
        self._stop.clear()

        def _run():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception as e:   # noqa: BLE001
                    self._last_error = f"poll failed: {e!r}"
                    monitor.counter_add("serving.poll_failures")
                self._stop.wait(self.poll_s)

        self._thread = mon_ctx.spawn(_run, name="serving-tailer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self._http is not None:
            self._http.shutdown()
            self._http = None

    # ---- health endpoint -------------------------------------------------

    def _start_health_endpoint(self, port: int) -> None:
        """Tiny stdlib HTTP endpoint: ``/healthz`` returns the health()
        JSON (200 while a model serves, 503 before the first load),
        ``/metrics`` the telemetry hub's Prometheus exposition — the
        operator surface the runbook (README) curls."""
        server = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API)
                if self.path.startswith("/healthz"):
                    body = json.dumps(server.health()).encode()
                    code = 503 if server._active is None else 200
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    body = monitor.hub().prometheus_text().encode()
                    code, ctype = 200, "text/plain; version=0.0.4"
                else:
                    body, code, ctype = b"not found", 404, "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # quiet: telemetry is the log
                pass

        self._http = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                                     _Handler)
        self.health_port = self._http.server_address[1]
        mon_ctx.spawn(self._http.serve_forever,
                      name="serving-health").start()


def _normalize_cfg(cfg: dict) -> dict:
    return {k: (tuple(v) if isinstance(v, list) else v)
            for k, v in cfg.items()}


def main(argv: list[str] | None = None) -> int:
    """Runbook entrypoint (README "Serving runbook"):
    ``python -m paddlebox_tpu.serving.server ROOT [--health-port N]``
    tails ROOT's donefile forever, hot-swapping each announced version
    and serving /healthz + /metrics."""
    import argparse
    ap = argparse.ArgumentParser(
        description="Serve the newest verified model published to ROOT "
                    "(tails serving_model.donefile; hot-swaps new "
                    "versions under load; degrades to the last good "
                    "version when publishes stop or verification fails)")
    ap.add_argument("root", help="serving root (local dir or hdfs:// URI)")
    ap.add_argument("--poll-s", type=float, default=1.0)
    ap.add_argument("--health-port", type=int, default=8080,
                    help="0 picks a free port; printed on startup")
    ap.add_argument("--staging-dir", default=None,
                    help="where remote artifacts download before verify")
    ap.add_argument("--stale-pass-lag", type=int, default=2)
    ap.add_argument("--stale-after-s", type=float, default=600.0)
    args = ap.parse_args(argv)
    srv = ServingServer(args.root, poll_s=args.poll_s,
                        staging_dir=args.staging_dir,
                        stale_pass_lag=args.stale_pass_lag,
                        stale_after_s=args.stale_after_s,
                        health_port=args.health_port).start()
    print(f"serving {args.root}; health at "
          f"http://127.0.0.1:{srv.health_port}/healthz", flush=True)
    try:
        while True:
            time.sleep(60)
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
