"""User-side data generator protocol.

Reference: ``MultiSlotDataGenerator``
(python/paddle/fluid/incubate/data_generator, and
python/paddle/distributed/fleet/data_generator): users subclass it, define
``generate_sample(line)`` yielding ``[(slot_name, values), ...]`` per
example, and run the script as a ``pipe_command`` — the framework consumes
the MultiSlot text it prints on stdout.

Identical contract here; the output is exactly what
``parse_multislot_lines`` / the native parser read.
"""

from __future__ import annotations

import sys
from typing import Iterable, Iterator, Sequence

from paddlebox_tpu.data.parser import format_multislot_example
from paddlebox_tpu.data.schema import DataFeedSchema


class MultiSlotDataGenerator:
    """Subclass and override ``generate_sample``."""

    def __init__(self, schema: DataFeedSchema):
        self.schema = schema

    def generate_sample(self, line: str) -> Iterator[
            Sequence[tuple[str, Sequence]]]:
        """Yield zero or more examples for one raw input line; each example
        is a sequence of (slot_name, values) pairs."""
        raise NotImplementedError

    # ---- the pipe_command entry points ----

    def process(self, lines: Iterable[str], out=None) -> int:
        out = out or sys.stdout
        n = 0
        for line in lines:
            for example in self.generate_sample(line.rstrip("\n")):
                out.write(format_multislot_example(example, self.schema))
                out.write("\n")
                n += 1
        return n

    def run_from_stdin(self) -> None:
        """`cat raw | python my_generator.py` as the dataset pipe_command."""
        self.process(sys.stdin)
