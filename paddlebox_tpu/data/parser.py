"""Slot-format text parsing.

The reference parses slot-formatted examples three ways (SlotPaddleBoxDataFeed,
reference data_feed.cc:3104-3115): built-in ``ParseOneInstance`` for the
MultiSlot text protocol, a dlopen'd parser plugin (``ISlotParser``,
data_feed.h:1283), or an arbitrary ``pipe_command`` whose stdout is the
MultiSlot protocol. We keep all three ingestion modes (see ``reader.py``); this
module holds the protocol parser itself, with two implementations:

- a vectorized numpy fallback (pure Python), and
- a native C++ parser (``paddlebox_tpu/native/slot_parser.cc``) loaded via
  ctypes, which is the production path — the reference burns dozens of host
  parser threads per node (platform/flags.cc:480-484) and host-side parse is
  the known ingest bottleneck (SURVEY.md §7 "Hard parts").

MultiSlot text protocol: for each example (one line), for each slot in schema
order: ``<len> v_1 ... v_len`` separated by whitespace. uint64 slots carry
feature signs, float slots carry floats. Lines may optionally be prefixed with
``<ins_id>\\t`` when the schema's reader enables instance ids.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from paddlebox_tpu.data.schema import DataFeedSchema, SlotType
from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.utils.hashing import hash64


def parse_multislot_lines(
    lines: Iterable[str],
    schema: DataFeedSchema,
    with_ins_id: bool = False,
) -> SlotRecordBatch:
    """Parse MultiSlot text lines into one columnar SlotRecordBatch."""
    native = _maybe_native()
    if native is not None:
        lines = list(lines)      # re-iterable for the fallback below
        try:
            out = native.parse_lines(lines, schema, with_ins_id=with_ins_id)
        except ValueError:
            # the native fast path is strict (first bad line raises);
            # re-parse in Python, which applies the skip-with-a-name
            # malformed-line treatment (reader.parse_errors) — the
            # contract must not depend on whether the .so is built
            out = None
        if out is not None:
            return out
    return _parse_python(lines, schema, with_ins_id)


def parse_multislot_buffer(
    buf: bytes,
    schema: DataFeedSchema,
    with_ins_id: bool = False,
) -> SlotRecordBatch:
    """Parse a whole raw text buffer — the zero-copy native fast path (the
    file reader hands bytes straight to C++, no Python line iteration)."""
    native = _maybe_native()
    if native is not None:
        try:
            out = native.parse_buffer(buf, schema, with_ins_id=with_ins_id)
        except ValueError:
            out = None           # strict native parser: fall back (above)
        if out is not None:
            return out
    # errors="replace", not strict: a torn line of binary garbage must
    # reach the per-line skip logic (reader.parse_errors), not brick the
    # whole file with an UnicodeDecodeError that names nothing
    return _parse_python(buf.decode("utf-8", errors="replace").splitlines(),
                         schema, with_ins_id)


_U64_MASK = (1 << 64) - 1
_U64_WRAP = 1 << 64
_I64_MAX1 = 1 << 63


def _note_malformed_line(lineno: int, line: str, err: Exception,
                         n_bad: int) -> None:
    """Malformed-line diagnostics: every skip counts, the first few per
    parse call carry the line's identity, and the first warns — the
    skip-with-a-name discipline of FleetUtil._entries (PR-7)."""
    from paddlebox_tpu import monitor
    monitor.counter_add("reader.parse_errors")
    if n_bad <= 5:    # identity for the head; the counter carries the rest
        monitor.event("reader_malformed_line", lineno=lineno,
                      error=str(err)[:200], line=line[:120])
    if n_bad == 1:
        import warnings
        warnings.warn(
            f"malformed MultiSlot line {lineno} (skipped): "
            f"{line[:120]!r} ({err}); counting under reader.parse_errors")


def _wrap_i64(v: str) -> int:
    u = int(v) & _U64_MASK
    return u - _U64_WRAP if u >= _I64_MAX1 else u


def _parse_python(lines: Iterable[str], schema: DataFeedSchema,
                  with_ins_id: bool) -> SlotRecordBatch:
    slots = schema.slots
    n_sparse = len(schema.sparse_slots)
    n_float = len(schema.float_slots)
    sparse_vals: list[list[int]] = [[] for _ in range(n_sparse)]
    sparse_lens: list[list[int]] = [[] for _ in range(n_sparse)]
    float_vals: list[list[float]] = [[] for _ in range(n_float)]
    ins_ids: list[int] = []
    num = 0
    n_bad = 0
    lineno = 0
    for line in lines:
        lineno += 1
        line = line.strip()
        if not line:
            continue
        # parse into per-LINE buffers and commit to the columns only on
        # success: a line failing mid-slot leaves no partial state, with
        # zero happy-path rollback bookkeeping
        row_ins = 0
        row_sparse: list[tuple[list[int], int]] = []
        row_float: list[list[float]] = []
        try:
            if with_ins_id:
                ins_id_str, _, line = line.partition("\t")
                row_ins = hash64(ins_id_str)
            toks = line.split()
            pos = 0
            for slot in slots:
                if pos >= len(toks):
                    raise ValueError(
                        f"ran out of tokens at slot {slot.name!r}")
                ln = int(toks[pos]); pos += 1
                if ln < 0:
                    # a negative length passes the bounds check below
                    # (empty slice, pos moves BACKWARDS) and would emit
                    # negative sparse_lens — silent batch corruption
                    raise ValueError(
                        f"slot {slot.name!r} declares negative length {ln}")
                if pos + ln > len(toks):
                    raise ValueError(
                        f"slot {slot.name!r} declares {ln} values but "
                        f"line ends")
                vals = toks[pos:pos + ln]; pos += ln
                if slot.type == SlotType.UINT64:
                    if slot.is_used:
                        # Feature signs are full-range uint64; storage is
                        # int64 bit patterns (reinterpret, like the native
                        # parser), so signs >= 2^63 wrap instead of
                        # overflowing.
                        row_sparse.append(
                            ([_wrap_i64(v) for v in vals], ln))
                else:
                    if slot.is_used:
                        w = slot.max_len
                        fv = [float(v) for v in vals[:w]]
                        fv += [0.0] * (w - len(fv))
                        row_float.append(fv)
        except ValueError as err:
            # A torn/foreign line must not brick the whole file: skip it
            # WITH A NAME — counter + event carrying the line's identity —
            # the same treatment PR-7 gave malformed donefile lines. An
            # input that parses to NOTHING still raises below: dirty data
            # is survivable, a wrong schema or binary garbage is not.
            n_bad += 1
            _note_malformed_line(lineno, line, err, n_bad)
            continue
        for i, (vals_i, ln_i) in enumerate(row_sparse):
            sparse_vals[i].extend(vals_i)
            sparse_lens[i].append(ln_i)
        for i, fv_i in enumerate(row_float):
            float_vals[i].extend(fv_i)
        if with_ins_id:
            ins_ids.append(row_ins)
        num += 1
    if num == 0 and n_bad:
        raise ValueError(
            f"every line was malformed MultiSlot ({n_bad} skipped) — "
            f"wrong schema or non-MultiSlot input?")
    sparse_values = [np.asarray(v, dtype=np.int64) for v in sparse_vals]
    sparse_offsets = []
    for lens in sparse_lens:
        offs = np.zeros(num + 1, dtype=np.int64)
        if lens:
            np.cumsum(np.asarray(lens, dtype=np.int64), out=offs[1:])
        sparse_offsets.append(offs)
    if not with_ins_id:
        ins = np.zeros(num, dtype=np.uint64)
    else:
        ins = np.asarray(ins_ids, dtype=np.uint64)
    return SlotRecordBatch(
        schema=schema, num=num,
        sparse_values=sparse_values, sparse_offsets=sparse_offsets,
        float_values=[np.asarray(v, dtype=np.float32) for v in float_vals],
        ins_id=ins,
        search_id=np.zeros(num, dtype=np.uint64),
        rank=np.zeros(num, dtype=np.int32),
        cmatch=np.zeros(num, dtype=np.int32),
    )


_native_cache: list = []


def _maybe_native():
    """Lazy-load the C++ parser; None if the shared lib isn't built."""
    if not _native_cache:
        try:
            from paddlebox_tpu.native import slot_parser_binding
            _native_cache.append(slot_parser_binding)
        except Exception:
            _native_cache.append(None)
    return _native_cache[0]


def format_multislot_example(slot_values: Sequence[tuple[str, Sequence]],
                             schema: DataFeedSchema) -> str:
    """Inverse of the parser — used by the data generator (the reference's
    MultiSlotDataGenerator protocol, python/paddle/fluid/incubate/data_generator)."""
    by_name = dict(slot_values)
    parts: list[str] = []
    for slot in schema.slots:
        vals = by_name.get(slot.name, ())
        parts.append(str(len(vals)))
        parts.extend(str(v) for v in vals)
    return " ".join(parts)
