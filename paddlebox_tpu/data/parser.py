"""Slot-format text parsing.

The reference parses slot-formatted examples three ways (SlotPaddleBoxDataFeed,
reference data_feed.cc:3104-3115): built-in ``ParseOneInstance`` for the
MultiSlot text protocol, a dlopen'd parser plugin (``ISlotParser``,
data_feed.h:1283), or an arbitrary ``pipe_command`` whose stdout is the
MultiSlot protocol. We keep all three ingestion modes (see ``reader.py``); this
module holds the protocol parser itself, with two implementations:

- a vectorized numpy fallback (pure Python), and
- a native C++ parser (``paddlebox_tpu/native/slot_parser.cc``) loaded via
  ctypes, which is the production path — the reference burns dozens of host
  parser threads per node (platform/flags.cc:480-484) and host-side parse is
  the known ingest bottleneck (SURVEY.md §7 "Hard parts").

MultiSlot text protocol: for each example (one line), for each slot in schema
order: ``<len> v_1 ... v_len`` separated by whitespace. uint64 slots carry
feature signs, float slots carry floats. Lines may optionally be prefixed with
``<ins_id>\\t`` when the schema's reader enables instance ids.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from paddlebox_tpu.data.schema import DataFeedSchema, SlotType
from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.utils.hashing import hash64


def parse_multislot_lines(
    lines: Iterable[str],
    schema: DataFeedSchema,
    with_ins_id: bool = False,
) -> SlotRecordBatch:
    """Parse MultiSlot text lines into one columnar SlotRecordBatch."""
    native = _maybe_native()
    if native is not None:
        out = native.parse_lines(lines, schema, with_ins_id=with_ins_id)
        if out is not None:
            return out
    return _parse_python(lines, schema, with_ins_id)


def parse_multislot_buffer(
    buf: bytes,
    schema: DataFeedSchema,
    with_ins_id: bool = False,
) -> SlotRecordBatch:
    """Parse a whole raw text buffer — the zero-copy native fast path (the
    file reader hands bytes straight to C++, no Python line iteration)."""
    native = _maybe_native()
    if native is not None:
        out = native.parse_buffer(buf, schema, with_ins_id=with_ins_id)
        if out is not None:
            return out
    return _parse_python(buf.decode("utf-8").splitlines(), schema,
                         with_ins_id)


_U64_MASK = (1 << 64) - 1
_U64_WRAP = 1 << 64
_I64_MAX1 = 1 << 63


def _wrap_i64(v: str) -> int:
    u = int(v) & _U64_MASK
    return u - _U64_WRAP if u >= _I64_MAX1 else u


def _parse_python(lines: Iterable[str], schema: DataFeedSchema,
                  with_ins_id: bool) -> SlotRecordBatch:
    slots = schema.slots
    n_sparse = len(schema.sparse_slots)
    n_float = len(schema.float_slots)
    sparse_vals: list[list[int]] = [[] for _ in range(n_sparse)]
    sparse_lens: list[list[int]] = [[] for _ in range(n_sparse)]
    float_vals: list[list[float]] = [[] for _ in range(n_float)]
    ins_ids: list[int] = []
    num = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        if with_ins_id:
            ins_id_str, _, line = line.partition("\t")
            ins_ids.append(hash64(ins_id_str))
        toks = line.split()
        pos = 0
        si = fi = 0
        for slot in slots:
            if pos >= len(toks):
                raise ValueError(
                    f"malformed MultiSlot line (ran out of tokens at slot "
                    f"{slot.name!r}, example {num}): {line[:120]!r}")
            ln = int(toks[pos]); pos += 1
            if pos + ln > len(toks):
                raise ValueError(
                    f"malformed MultiSlot line (slot {slot.name!r} declares "
                    f"{ln} values but line ends, example {num}): {line[:120]!r}")
            vals = toks[pos:pos + ln]; pos += ln
            if slot.type == SlotType.UINT64:
                if slot.is_used:
                    # Feature signs are full-range uint64; storage is int64
                    # bit patterns (reinterpret, like the native parser), so
                    # signs >= 2^63 wrap instead of overflowing.
                    sparse_vals[si].extend(map(_wrap_i64, vals))
                    sparse_lens[si].append(ln)
                    si += 1
            else:
                if slot.is_used:
                    w = slot.max_len
                    fv = [float(v) for v in vals[:w]]
                    fv += [0.0] * (w - len(fv))
                    float_vals[fi].extend(fv)
                    fi += 1
        num += 1
    sparse_values = [np.asarray(v, dtype=np.int64) for v in sparse_vals]
    sparse_offsets = []
    for lens in sparse_lens:
        offs = np.zeros(num + 1, dtype=np.int64)
        if lens:
            np.cumsum(np.asarray(lens, dtype=np.int64), out=offs[1:])
        sparse_offsets.append(offs)
    if not with_ins_id:
        ins = np.zeros(num, dtype=np.uint64)
    else:
        ins = np.asarray(ins_ids, dtype=np.uint64)
    return SlotRecordBatch(
        schema=schema, num=num,
        sparse_values=sparse_values, sparse_offsets=sparse_offsets,
        float_values=[np.asarray(v, dtype=np.float32) for v in float_vals],
        ins_id=ins,
        search_id=np.zeros(num, dtype=np.uint64),
        rank=np.zeros(num, dtype=np.int32),
        cmatch=np.zeros(num, dtype=np.int32),
    )


_native_cache: list = []


def _maybe_native():
    """Lazy-load the C++ parser; None if the shared lib isn't built."""
    if not _native_cache:
        try:
            from paddlebox_tpu.native import slot_parser_binding
            _native_cache.append(slot_parser_binding)
        except Exception:
            _native_cache.append(None)
    return _native_cache[0]


def format_multislot_example(slot_values: Sequence[tuple[str, Sequence]],
                             schema: DataFeedSchema) -> str:
    """Inverse of the parser — used by the data generator (the reference's
    MultiSlotDataGenerator protocol, python/paddle/fluid/incubate/data_generator)."""
    by_name = dict(slot_values)
    parts: list[str] = []
    for slot in schema.slots:
        vals = by_name.get(slot.name, ())
        parts.append(str(len(vals)))
        parts.extend(str(v) for v in vals)
    return " ".join(parts)
