"""Streaming dataset — the QueueDataset equivalent.

The reference has two dataset modes (data_set.h:175-346,
python/paddle/fluid/dataset.py): ``InMemoryDataset`` (load the pass, shuffle,
train — our SlotDataset) and ``QueueDataset``, which streams files through
bounded channels straight to the trainers: single epoch, no global shuffle,
memory bounded by channel capacity rather than pass size.

Here reader threads parse files into columnar chunks feeding a bounded
queue; the consumer restitches chunks into fixed-size ``PackedBatch``es.
Memory high-water = ``queue_capacity`` chunks + one batch remainder,
independent of pass size.

For training with the HBM working-set path a pass's unique keys must be
known up front, which streaming cannot provide — so QueueDataset pairs with
``HeterTrainer`` (host-resident table, no pass working set) or with a
replicated/cached table. This mirrors the reference, where QueueDataset is
the PSLib/CPU-trainer mode while BoxPS uses the in-memory pass dataset
(SURVEY.md §2.2).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Sequence

import numpy as np

from paddlebox_tpu.data.reader import ParserPlugin, read_file
from paddlebox_tpu.data.schema import DataFeedSchema
from paddlebox_tpu.data.slot_record import PackedBatch, SlotRecordBatch
from paddlebox_tpu.monitor import context as mon_ctx
from paddlebox_tpu.monitor import counter_add as stat_add

_STOP = object()


class QueueDataset:
    """Bounded-memory streaming over a filelist."""

    def __init__(self, schema: DataFeedSchema, num_threads: int = 2,
                 queue_capacity: int = 8):
        self.schema = schema
        self.filelist: list[str] = []
        self.pipe_command: str | None = None
        self.parser_plugin: ParserPlugin | None = None
        self.num_threads = max(1, num_threads)
        self.queue_capacity = queue_capacity

    # ---- configuration (dataset.py QueueDataset API) ----
    def set_filelist(self, files: Sequence[str]) -> None:
        self.filelist = list(files)

    def set_pipe_command(self, cmd: str | None) -> None:
        self.pipe_command = cmd

    def set_parser_plugin(self, plugin: ParserPlugin | None) -> None:
        self.parser_plugin = plugin

    # ---- streaming ----
    def _chunks(self, files: Sequence[str]) -> Iterator[SlotRecordBatch]:
        """Parse `files` with a reader-thread pool; yield columnar chunks in
        completion order (the reference's channel semantics — order across
        files is not guaranteed).

        Abandoning the iterator early (break / next-once) shuts the workers
        down via `cancel`: puts are bounded-wait so a blocked worker notices
        cancellation instead of leaking forever against the full queue."""
        q: queue.Queue = queue.Queue(maxsize=self.queue_capacity)
        it = iter(files)
        it_lock = threading.Lock()
        cancel = threading.Event()
        errors: list[BaseException] = []

        def _put(item) -> bool:
            while not cancel.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                while not cancel.is_set():
                    with it_lock:
                        path = next(it, None)
                    if path is None:
                        break
                    chunk = read_file(path, self.schema,
                                      pipe_command=self.pipe_command,
                                      parser_plugin=self.parser_plugin)
                    stat_add("queue_dataset_examples", chunk.num)
                    if not _put(chunk):
                        return
            except BaseException as e:  # surfaced to the consumer
                errors.append(e)
            finally:
                _put(_STOP) or q.put(_STOP)  # sentinel must always land

        n = min(self.num_threads, max(1, len(files)))
        threads = [mon_ctx.spawn(worker) for _ in range(n)]
        for t in threads:
            t.start()
        done = 0
        try:
            while done < n:
                item = q.get()
                if item is _STOP:
                    done += 1
                    continue
                yield item
        finally:
            cancel.set()
            # unblock any worker stuck on a full queue, then reap
            while done < n:
                item = q.get()
                if item is _STOP:
                    done += 1
            for t in threads:
                t.join()
        if errors:
            raise errors[0]

    def batches(self, batch_size: int | None = None,
                drop_last: bool = True,
                files: Sequence[str] | None = None
                ) -> Iterator[PackedBatch]:
        """Stream fixed-size PackedBatches; chunk remainders are stitched
        across file boundaries."""
        bs = batch_size or self.schema.batch_size
        pending: list[SlotRecordBatch] = []
        have = 0
        for chunk in self._chunks(self.filelist if files is None else files):
            pending.append(chunk)
            have += chunk.num
            if have < bs:
                continue
            # one concat per stitch group, then a sliding pack cursor —
            # only the < bs tail is re-materialized via select
            merged = SlotRecordBatch.concat(pending)
            off = 0
            while off + bs <= merged.num:
                yield merged.pack(off, off + bs)
                off += bs
            have = merged.num - off
            pending = ([merged.select(np.arange(off, merged.num))]
                       if have else [])
        if have and not drop_last:
            merged = SlotRecordBatch.concat(pending)
            yield merged.pack(0, merged.num)

    def shard_batches(self, shard: int, num_shards: int,
                      batch_size: int | None = None,
                      drop_last: bool = True) -> Iterator[PackedBatch]:
        """File-level sharding for multi-worker streaming (the reference
        assigns whole files round-robin to its readers)."""
        files = [f for i, f in enumerate(self.filelist)
                 if i % num_shards == shard]
        return self.batches(batch_size, drop_last, files=files)
