"""File readers: the three ingestion modes of SlotPaddleBoxDataFeed.

Reference (data_feed.cc:3104-3115):
- ``LoadIntoMemoryByCommand`` — popen a ``pipe_command`` whose stdout is the
  MultiSlot protocol;
- ``LoadIntoMemoryByLib`` — dlopen'd parser plugin (``ISlotParser``);
- built-in line parsing of local/HDFS files.

Here a *parser plugin* is any Python callable
``(iter[str], DataFeedSchema) -> SlotRecordBatch`` registered by module path
(``"pkg.mod:func"``) — the dlopen moral equivalent without the .so contract —
and pipe commands work identically (stdout → protocol parser). Gzip files are
handled transparently, like the reference's file managers.
"""

from __future__ import annotations

import gzip
import importlib
import os
import subprocess
from typing import Callable, Iterable, Iterator

from paddlebox_tpu import monitor
from paddlebox_tpu.data.parser import parse_multislot_buffer
from paddlebox_tpu.data.schema import DataFeedSchema
from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.utils import fs as fs_lib

ParserPlugin = Callable[[Iterable[str], DataFeedSchema], SlotRecordBatch]


def open_lines(path: str) -> Iterator[str]:
    """Stream text lines from a local or remote (scheme-carrying) path."""
    fs, p = fs_lib.resolve(path)
    raw = fs.open_read(p)
    try:
        if path.endswith(".gz"):
            with gzip.open(raw, "rt") as f:
                yield from f
        else:
            for line in raw:
                yield line.decode("utf-8", errors="replace")
    finally:
        raw.close()


def load_parser_plugin(spec: str) -> ParserPlugin:
    """Resolve ``"package.module:callable"`` — our ISlotParser dlopen
    equivalent (reference data_feed.cc:2812 caches dlopen'd .so parsers)."""
    mod_name, _, attr = spec.partition(":")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, attr or "parse")
    if not callable(fn):
        raise TypeError(f"parser plugin {spec!r} is not callable")
    return fn


def read_file(
    path: str,
    schema: DataFeedSchema,
    pipe_command: str | None = None,
    parser_plugin: ParserPlugin | None = None,
    with_ins_id: bool = False,
) -> SlotRecordBatch:
    """Read one file into a columnar batch via the configured ingestion mode."""
    if path.endswith(".pbar"):  # pre-tokenized binary archive
        from paddlebox_tpu.data.archive import read_archive
        if fs_lib.is_remote(path):
            # npz wants a seekable file: stage remote archives locally
            import tempfile
            fs, p = fs_lib.resolve(path)
            with tempfile.TemporaryDirectory() as d:
                local = os.path.join(d, os.path.basename(p))
                fs.get(p, local)
                return read_archive(local, schema)
        return read_archive(path, schema)
    if pipe_command:
        if path and fs_lib.is_remote(path):
            # remote input: the filesystem's cat streams into the command's
            # stdin (the reference's HDFS reads ride the pipe the same way).
            # The feed runs on its own thread — writing all of stdin before
            # reading stdout deadlocks once either pipe buffer fills.
            import shutil as _sh

            from paddlebox_tpu.monitor import context as _mon_ctx
            fs, p = fs_lib.resolve(path)
            src = fs.open_read(p)
            proc = subprocess.Popen(pipe_command, shell=True,
                                    stdin=subprocess.PIPE,
                                    stdout=subprocess.PIPE)
            assert proc.stdin is not None and proc.stdout is not None
            feed_err: list = []

            def _feed():
                try:
                    try:
                        _sh.copyfileobj(src, proc.stdin)
                    # pblint: disable=silent-except -- consumer exited early
                    # (head-style sampling commands close the pipe after
                    # enough bytes); by design not an error, nothing to count
                    except BrokenPipeError:
                        pass
                    except BaseException as e:  # surfaced after the read
                        feed_err.append(e)
                finally:
                    for f in (proc.stdin, src):
                        try:
                            f.close()
                        except Exception as e:
                            # teardown failures are non-fatal (the pipe may
                            # already be broken) but never invisible
                            monitor.counter_add("reader.close_errors")
                            monitor.event("reader_close_error",
                                          path=path, error=repr(e)[:200])

            feeder = _mon_ctx.spawn(_feed)
            feeder.start()
        else:
            feeder = None
            feed_err = []
            proc = subprocess.Popen(
                f"{pipe_command} < {path}" if path else pipe_command,
                shell=True, stdout=subprocess.PIPE,
            )
        assert proc.stdout is not None
        try:
            buf = proc.stdout.read()
        finally:
            ret = proc.wait()
            if feeder is not None:
                feeder.join()
        if feed_err:
            raise RuntimeError(
                f"remote read into pipe_command {pipe_command!r} failed"
            ) from feed_err[0]
        if ret != 0:
            raise RuntimeError(f"pipe_command {pipe_command!r} exited {ret}")
        return parse_multislot_buffer(buf, schema, with_ins_id=with_ins_id)
    if parser_plugin is not None:
        return parser_plugin(open_lines(path), schema)
    if fs_lib.is_remote(path):
        fs, p = fs_lib.resolve(path)
        with fs.open_read(p) as f:
            buf = f.read()
        if path.endswith(".gz"):
            buf = gzip.decompress(buf)
        return parse_multislot_buffer(buf, schema, with_ins_id=with_ins_id)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        buf = f.read()
    return parse_multislot_buffer(buf, schema, with_ins_id=with_ins_id)
