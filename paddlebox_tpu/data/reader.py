"""File readers: the three ingestion modes of SlotPaddleBoxDataFeed.

Reference (data_feed.cc:3104-3115):
- ``LoadIntoMemoryByCommand`` — popen a ``pipe_command`` whose stdout is the
  MultiSlot protocol;
- ``LoadIntoMemoryByLib`` — dlopen'd parser plugin (``ISlotParser``);
- built-in line parsing of local/HDFS files.

Here a *parser plugin* is any Python callable
``(iter[str], DataFeedSchema) -> SlotRecordBatch`` registered by module path
(``"pkg.mod:func"``) — the dlopen moral equivalent without the .so contract —
and pipe commands work identically (stdout → protocol parser). Gzip files are
handled transparently, like the reference's file managers.
"""

from __future__ import annotations

import gzip
import importlib
import subprocess
from typing import Callable, Iterable, Iterator

from paddlebox_tpu.data.parser import parse_multislot_buffer
from paddlebox_tpu.data.schema import DataFeedSchema
from paddlebox_tpu.data.slot_record import SlotRecordBatch

ParserPlugin = Callable[[Iterable[str], DataFeedSchema], SlotRecordBatch]


def open_lines(path: str) -> Iterator[str]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:  # type: ignore[arg-type]
        yield from f


def load_parser_plugin(spec: str) -> ParserPlugin:
    """Resolve ``"package.module:callable"`` — our ISlotParser dlopen
    equivalent (reference data_feed.cc:2812 caches dlopen'd .so parsers)."""
    mod_name, _, attr = spec.partition(":")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, attr or "parse")
    if not callable(fn):
        raise TypeError(f"parser plugin {spec!r} is not callable")
    return fn


def read_file(
    path: str,
    schema: DataFeedSchema,
    pipe_command: str | None = None,
    parser_plugin: ParserPlugin | None = None,
    with_ins_id: bool = False,
) -> SlotRecordBatch:
    """Read one file into a columnar batch via the configured ingestion mode."""
    if path.endswith(".pbar"):  # pre-tokenized binary archive
        from paddlebox_tpu.data.archive import read_archive
        return read_archive(path, schema)
    if pipe_command:
        proc = subprocess.Popen(
            f"{pipe_command} < {path}" if path else pipe_command,
            shell=True, stdout=subprocess.PIPE,
        )
        assert proc.stdout is not None
        try:
            buf = proc.stdout.read()
        finally:
            ret = proc.wait()
        if ret != 0:
            raise RuntimeError(f"pipe_command {pipe_command!r} exited {ret}")
        return parse_multislot_buffer(buf, schema, with_ins_id=with_ins_id)
    if parser_plugin is not None:
        return parser_plugin(open_lines(path), schema)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        buf = f.read()
    return parse_multislot_buffer(buf, schema, with_ins_id=with_ins_id)
