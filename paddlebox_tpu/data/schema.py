"""Slot schema — the DataFeedDesc/Slot equivalent.

The reference describes its input with a protobuf ``DataFeedDesc`` whose
``MultiSlotDesc`` lists ``Slot{name, type, is_dense, is_used, shape}``
(reference: paddle/fluid/framework/data_feed.proto:17-37). We use a typed
dataclass instead, and add the one thing XLA demands that LoD tensors never
needed: a static ``max_len`` per sparse slot, so every batch has a fixed
(batch, max_len) shape on device (SURVEY.md §7 "Static-shape discipline").
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Sequence


class SlotType(enum.Enum):
    UINT64 = "uint64"   # feature-sign (hashed feature id) slots
    FLOAT = "float"     # dense float slots (e.g. 13 Criteo numeric features)


@dataclasses.dataclass(frozen=True)
class Slot:
    """One input slot.

    ``max_len`` bounds the ids per example for sparse slots (longer lists are
    truncated, shorter padded); for float slots it is the fixed feature width.
    """

    name: str
    type: SlotType = SlotType.UINT64
    is_dense: bool = False
    is_used: bool = True
    max_len: int = 1

    def __post_init__(self) -> None:
        if self.max_len < 1:
            raise ValueError(f"slot {self.name}: max_len must be >= 1")


@dataclasses.dataclass(frozen=True)
class DataFeedSchema:
    """Ordered slot list + batch geometry for one dataset."""

    slots: tuple[Slot, ...]
    batch_size: int = 64

    def __init__(self, slots: Sequence[Slot], batch_size: int = 64):
        object.__setattr__(self, "slots", tuple(slots))
        object.__setattr__(self, "batch_size", int(batch_size))
        names = [s.name for s in self.slots]
        if len(set(names)) != len(names):
            raise ValueError("duplicate slot names in schema")

    @property
    def sparse_slots(self) -> tuple[Slot, ...]:
        return tuple(s for s in self.slots if s.type == SlotType.UINT64 and s.is_used)

    @property
    def float_slots(self) -> tuple[Slot, ...]:
        return tuple(s for s in self.slots if s.type == SlotType.FLOAT and s.is_used)

    @property
    def use_slots(self) -> tuple[Slot, ...]:
        return tuple(s for s in self.slots if s.is_used)

    def float_split_cols(self, label_slot: str) -> tuple[int, int, int]:
        """(label_col, label_width, total_float_cols) over the packed float
        columns; label_col is -1 when `label_slot` is absent (legal at
        serving time — training callers should treat that as an error)."""
        col, lc, lw = 0, -1, 0
        for slot in self.float_slots:
            if slot.name == label_slot:
                lc, lw = col, slot.max_len
            col += slot.max_len
        return lc, lw, col

    def slot_index(self, name: str) -> int:
        for i, s in enumerate(self.slots):
            if s.name == name:
                return i
        raise KeyError(name)

    @staticmethod
    def ctr(num_sparse: int, num_float: int = 0, batch_size: int = 64,
            max_len: int = 1, label_slot: str = "label") -> "DataFeedSchema":
        """Convenience constructor for synthetic CTR schemas used in tests.

        Layout mirrors Criteo-style data: a label slot, ``num_float`` dense
        floats, ``num_sparse`` uint64 feature slots.
        """
        slots = [Slot(label_slot, SlotType.FLOAT, max_len=1)]
        slots += [Slot(f"dense_{i}", SlotType.FLOAT, max_len=1)
                  for i in range(num_float)]
        slots += [Slot(f"slot_{i}", SlotType.UINT64, max_len=max_len)
                  for i in range(num_sparse)]
        return DataFeedSchema(slots, batch_size=batch_size)
