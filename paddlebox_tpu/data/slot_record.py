"""Columnar slot-record batches.

The reference stores each example as a malloc'd ``SlotRecord`` holding CSR-style
``SlotValues<uint64_t>`` + ``SlotValues<float>`` (values + per-slot offsets,
reference: paddle/fluid/framework/data_feed.h:778-862), pools them in a
``SlotObjPool`` and packs minibatches to GPU with ``MiniBatchGpuPack``
(data_feed.h:1372-1535, kernels in data_feed.cu).

TPU-native redesign: records are *columnar from the start* — one CSR block per
slot for a whole shard of examples (numpy host-side), so "packing a minibatch"
is pure vectorized slicing + padding, and the device-facing ``PackedBatch`` has
the static shapes XLA requires (ids ``(B, S, L)`` int32-indexed into the pass
working set or int64 raw keys, mask, floats, metadata columns).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Sequence

import numpy as np

from paddlebox_tpu.data.schema import DataFeedSchema, SlotType


@dataclasses.dataclass
class SlotRecordBatch:
    """A set of N examples, columnar CSR per slot (host side, ragged).

    sparse_values[s] : int64[nnz_s]   feature signs for sparse slot s
    sparse_offsets[s]: int64[N+1]     CSR offsets (example i owns
                                      values[offsets[i]:offsets[i+1]])
    float_values[f]  : float32[N * max_len_f]  fixed-width dense floats
    ins_id, search_id, rank, cmatch   metadata columns (reference
                                      data_feed.h:828-841)
    """

    schema: DataFeedSchema
    num: int
    sparse_values: list[np.ndarray]
    sparse_offsets: list[np.ndarray]
    float_values: list[np.ndarray]
    ins_id: np.ndarray          # uint64 hash of the instance id string
    search_id: np.ndarray       # uint64
    rank: np.ndarray            # int32
    cmatch: np.ndarray          # int32

    @classmethod
    def empty(cls, schema: DataFeedSchema) -> "SlotRecordBatch":
        ns = len(schema.sparse_slots)
        nf = len(schema.float_slots)
        return cls(
            schema=schema,
            num=0,
            sparse_values=[np.zeros(0, dtype=np.int64) for _ in range(ns)],
            sparse_offsets=[np.zeros(1, dtype=np.int64) for _ in range(ns)],
            float_values=[np.zeros(0, dtype=np.float32) for _ in range(nf)],
            ins_id=np.zeros(0, dtype=np.uint64),
            search_id=np.zeros(0, dtype=np.uint64),
            rank=np.zeros(0, dtype=np.int32),
            cmatch=np.zeros(0, dtype=np.int32),
        )

    # ---- combinators (the SlotObjPool merge path) ----

    @staticmethod
    def concat(batches: Sequence["SlotRecordBatch"]) -> "SlotRecordBatch":
        batches = [b for b in batches if b.num > 0]
        if not batches:
            raise ValueError("concat of empty batch list")
        first = batches[0]
        ns = len(first.sparse_values)
        nf = len(first.float_values)
        sparse_values, sparse_offsets = [], []
        for s in range(ns):
            sparse_values.append(np.concatenate([b.sparse_values[s] for b in batches]))
            offs = [first.sparse_offsets[s]]
            base = first.sparse_offsets[s][-1]
            for b in batches[1:]:
                offs.append(b.sparse_offsets[s][1:] + base)
                base += b.sparse_offsets[s][-1]
            sparse_offsets.append(np.concatenate(offs))
        return SlotRecordBatch(
            schema=first.schema,
            num=sum(b.num for b in batches),
            sparse_values=sparse_values,
            sparse_offsets=sparse_offsets,
            float_values=[np.concatenate([b.float_values[f] for b in batches])
                          for f in range(nf)],
            ins_id=np.concatenate([b.ins_id for b in batches]),
            search_id=np.concatenate([b.search_id for b in batches]),
            rank=np.concatenate([b.rank for b in batches]),
            cmatch=np.concatenate([b.cmatch for b in batches]),
        )

    def select(self, idx: np.ndarray) -> "SlotRecordBatch":
        """Row-subset (used by shuffle routing and per-device sharding)."""
        idx = np.asarray(idx, dtype=np.int64)
        sparse_values, sparse_offsets = [], []
        for vals, offs in zip(self.sparse_values, self.sparse_offsets):
            lens = offs[1:] - offs[:-1]
            sel_lens = lens[idx]
            new_offs = np.zeros(len(idx) + 1, dtype=np.int64)
            np.cumsum(sel_lens, out=new_offs[1:])
            # gather the ragged rows
            out = np.empty(new_offs[-1], dtype=np.int64)
            for j, i in enumerate(idx):
                out[new_offs[j]:new_offs[j + 1]] = vals[offs[i]:offs[i + 1]]
            sparse_values.append(out)
            sparse_offsets.append(new_offs)
        float_values = []
        for f, slot in enumerate(self.schema.float_slots):
            w = slot.max_len
            fv = self.float_values[f].reshape(self.num, w)[idx].reshape(-1)
            float_values.append(fv)
        return SlotRecordBatch(
            schema=self.schema, num=len(idx),
            sparse_values=sparse_values, sparse_offsets=sparse_offsets,
            float_values=float_values,
            ins_id=self.ins_id[idx], search_id=self.search_id[idx],
            rank=self.rank[idx], cmatch=self.cmatch[idx],
        )

    def shuffle(self, rng: np.random.Generator) -> "SlotRecordBatch":
        return self.select(rng.permutation(self.num))

    def unique_keys(self) -> np.ndarray:
        """All distinct feature signs in this batch — the FeedPass key
        extraction (reference MergeInsKeys data_set.cc:1786)."""
        if not self.sparse_values:
            return np.zeros(0, dtype=np.int64)
        return np.unique(np.concatenate(self.sparse_values))

    # ---- device packing (the MiniBatchGpuPack equivalent) ----

    def pack(self, start: int, end: int) -> "PackedBatch":
        """Pack examples [start, end) into fixed-shape arrays.

        Mirrors MiniBatchGpuPack::pack_instance + CopyForTensorKernel
        (reference data_feed.h:1379, data_feed.cu:35-206) but is a single
        vectorized numpy pass: per sparse slot, rows are truncated to the
        slot's max_len and padded with 0; mask records validity.
        """
        n = end - start
        schema = self.schema
        sslots = schema.sparse_slots
        ids_cols, mask_cols = [], []
        for s, slot in enumerate(sslots):
            offs = self.sparse_offsets[s]
            vals = self.sparse_values[s]
            lens = (offs[start + 1:end + 1] - offs[start:end])
            L = slot.max_len
            ids = np.zeros((n, L), dtype=np.int64)
            clip = np.minimum(lens, L)
            # vectorized ragged→padded: gather indices offs[i] + j for j < clip[i]
            row_idx = np.repeat(np.arange(n), clip)
            col_idx = _ranges(clip)
            src_idx = np.repeat(offs[start:end], clip) + col_idx
            ids[row_idx, col_idx] = vals[src_idx]
            mask = (col_idx_matrix(n, L) < clip[:, None])
            ids_cols.append(ids)
            mask_cols.append(mask)
        floats = []
        for f, slot in enumerate(schema.float_slots):
            w = slot.max_len
            floats.append(self.float_values[f].reshape(self.num, w)[start:end])
        # Flat (B, T) layout: slots with different max_len concatenate along
        # the token axis; static slot boundaries live in SparseLayout. One
        # device gather + one segment-sum covers all slots at once.
        return PackedBatch(
            schema=schema,
            num=n,
            ids=np.concatenate(ids_cols, axis=1) if ids_cols
                else np.zeros((n, 0), dtype=np.int64),
            mask=np.concatenate(mask_cols, axis=1) if mask_cols
                else np.zeros((n, 0), dtype=bool),
            floats=np.concatenate(floats, axis=1) if floats
                else np.zeros((n, 0), dtype=np.float32),
            rank=self.rank[start:end],
            cmatch=self.cmatch[start:end],
            ins_id=self.ins_id[start:end],
            search_id=self.search_id[start:end],
        )


def _ranges(lens: np.ndarray) -> np.ndarray:
    """[0..lens[0]), [0..lens[1]), ... concatenated."""
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    starts = np.cumsum(lens) - lens
    return np.arange(total, dtype=np.int64) - np.repeat(starts, lens)


def col_idx_matrix(n: int, L: int) -> np.ndarray:
    return np.broadcast_to(np.arange(L, dtype=np.int64), (n, L))


@dataclasses.dataclass(frozen=True)
class SparseLayout:
    """Static geometry of the flat (B, T) sparse-token axis.

    T = sum of max_len over sparse slots. ``segment_ids[t]`` maps token
    column t to its slot index — precomputed once per schema so pooling is a
    single ``segment_sum`` on device.
    """

    num_slots: int
    total_len: int
    slot_starts: np.ndarray    # int32 (S,)   first column of each slot
    slot_lens: np.ndarray      # int32 (S,)   = max_len per slot
    segment_ids: np.ndarray    # int32 (T,)   token column -> slot index

    @staticmethod
    def from_schema(schema: DataFeedSchema) -> "SparseLayout":
        lens = np.asarray([s.max_len for s in schema.sparse_slots], dtype=np.int32)
        starts = np.zeros_like(lens)
        if len(lens):
            starts[1:] = np.cumsum(lens)[:-1]
        return SparseLayout(
            num_slots=len(lens),
            total_len=int(lens.sum()),
            slot_starts=starts,
            slot_lens=lens,
            segment_ids=np.repeat(np.arange(len(lens), dtype=np.int32), lens),
        )


@dataclasses.dataclass
class PackedBatch:
    """Fixed-shape, device-ready minibatch.

    ids   : int64 (B, T) — raw feature signs, all sparse slots concatenated
            along the token axis (T = Σ max_len; see SparseLayout); the pass
            working set translates these to dense int32 indices before jit.
    mask  : bool  (B, T)
    floats: float32 (B, F_total) — concatenated fixed-width float slots,
            including the label column (schema order).
    """

    schema: DataFeedSchema
    num: int
    ids: np.ndarray
    mask: np.ndarray
    floats: np.ndarray
    rank: np.ndarray
    cmatch: np.ndarray
    ins_id: np.ndarray | None = None   # uint64 (B,) — DumpField's ins_id
    # uint64 (B,) PV group id — rank_attention models build rank_offset
    # from (rank, search_id); batches from merge_by_search_id keep a
    # PV's examples adjacent
    search_id: np.ndarray | None = None

    def layout(self) -> SparseLayout:
        return SparseLayout.from_schema(self.schema)

    def pad_to(self, batch_size: int) -> "PackedBatch":
        """Pad to `batch_size` rows with masked-out examples (tail batches
        keep the jitted step's static shape; padded rows carry mask=False
        everywhere so pulls resolve to padding and metrics can exclude them).
        """
        n = len(self.floats)
        if n >= batch_size:
            return self
        pad = batch_size - n

        def _pad(a, fill=0):
            shape = (pad,) + a.shape[1:]
            return np.concatenate([a, np.full(shape, fill, dtype=a.dtype)])

        return PackedBatch(
            schema=self.schema, num=self.num,
            ids=_pad(self.ids), mask=_pad(self.mask, False),
            floats=_pad(self.floats), rank=_pad(self.rank),
            cmatch=_pad(self.cmatch),
            ins_id=None if self.ins_id is None else _pad(self.ins_id),
            search_id=(None if self.search_id is None
                       else _pad(self.search_id)))

    def slot_ids(self, name: str) -> tuple[np.ndarray, np.ndarray]:
        """(ids, mask) view of one sparse slot, shape (B, max_len)."""
        lay = self.layout()
        for i, slot in enumerate(self.schema.sparse_slots):
            if slot.name == name:
                a, b = lay.slot_starts[i], lay.slot_starts[i] + lay.slot_lens[i]
                return self.ids[:, a:b], self.mask[:, a:b]
        raise KeyError(name)

    def label(self, label_slot: str = "label") -> np.ndarray:
        col = 0
        for slot in self.schema.float_slots:
            if slot.name == label_slot:
                return self.floats[:, col:col + slot.max_len].reshape(-1)
            col += slot.max_len
        raise KeyError(label_slot)

    def float_slot(self, name: str) -> np.ndarray:
        col = 0
        for slot in self.schema.float_slots:
            if slot.name == name:
                return self.floats[:, col:col + slot.max_len]
            col += slot.max_len
        raise KeyError(name)


def batch_iterator(records: SlotRecordBatch, batch_size: int,
                   drop_last: bool = False) -> Iterator[PackedBatch]:
    n = records.num
    end = (n // batch_size) * batch_size if drop_last else n
    for start in range(0, end, batch_size):
        yield records.pack(start, min(start + batch_size, end))
