from paddlebox_tpu.data.schema import Slot, SlotType, DataFeedSchema  # noqa: F401
from paddlebox_tpu.data.slot_record import (SlotRecordBatch, PackedBatch,  # noqa: F401
                                            SparseLayout)
from paddlebox_tpu.data.parser import parse_multislot_lines  # noqa: F401
from paddlebox_tpu.data.dataset import SlotDataset  # noqa: F401
from paddlebox_tpu.data.queue_dataset import QueueDataset  # noqa: F401
from paddlebox_tpu.data.archive import (write_archive, read_archive,  # noqa: F401
                                        archive_filelist)
