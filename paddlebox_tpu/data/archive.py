"""Binary columnar archive of parsed slot records.

The reference can dump parsed SlotRecords as a binary archive and reload
them without re-tokenizing text (BinaryArchiveWriter data_feed.h:1536,
``LoadIntoMemoryByArchive`` data_feed.cc; the pass pipeline's
"preload/archive" mode in PadBoxSlotDataset). Text parse is the ingest
bottleneck, so repeated passes over the same day's data should pay it once.

Format (``.pbar``): magic + little-endian uint64 header length + JSON header
+ raw column bytes in header order. Columns are exactly the
``SlotRecordBatch`` fields, so load is ``np.frombuffer`` per column — no
per-record work at all.
"""

from __future__ import annotations

import json
import os
from typing import Sequence

import numpy as np

from paddlebox_tpu.data.schema import DataFeedSchema
from paddlebox_tpu.data.slot_record import SlotRecordBatch

MAGIC = b"PBAR1\n"
ARCHIVE_SUFFIX = ".pbar"


def _columns(batch: SlotRecordBatch) -> list[tuple[str, np.ndarray]]:
    cols: list[tuple[str, np.ndarray]] = []
    for s, slot in enumerate(batch.schema.sparse_slots):
        cols.append((f"sparse_values/{slot.name}", batch.sparse_values[s]))
        cols.append((f"sparse_offsets/{slot.name}", batch.sparse_offsets[s]))
    for f, slot in enumerate(batch.schema.float_slots):
        cols.append((f"float_values/{slot.name}", batch.float_values[f]))
    cols.append(("ins_id", batch.ins_id))
    cols.append(("search_id", batch.search_id))
    cols.append(("rank", batch.rank))
    cols.append(("cmatch", batch.cmatch))
    return cols


def write_archive(path: str, batch: SlotRecordBatch) -> None:
    cols = _columns(batch)
    header = {
        "num": batch.num,
        "sparse_slots": [s.name for s in batch.schema.sparse_slots],
        "float_slots": [s.name for s in batch.schema.float_slots],
        "columns": [{"name": n, "dtype": str(a.dtype), "len": len(a)}
                    for n, a in cols],
    }
    hdr = json.dumps(header).encode("utf-8")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        f.write(np.uint64(len(hdr)).tobytes())
        f.write(hdr)
        for _, a in cols:
            f.write(np.ascontiguousarray(a).tobytes())
        # fsync before the rename: without it a power loss can leave the
        # FINAL name pointing at zero-length bytes (rename persisted, data
        # not) — the same tmp->fsync->replace discipline as atomic_file
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic: readers never see partial archives


def read_archive(path: str, schema: DataFeedSchema) -> SlotRecordBatch:
    with open(path, "rb") as f:
        buf = f.read()
    if buf[:len(MAGIC)] != MAGIC:
        raise ValueError(f"{path}: not a {MAGIC!r} archive")
    off = len(MAGIC)
    hlen = int(np.frombuffer(buf, np.uint64, 1, off)[0])
    off += 8
    header = json.loads(buf[off:off + hlen].decode("utf-8"))
    off += hlen
    want_sparse = [s.name for s in schema.sparse_slots]
    want_float = [s.name for s in schema.float_slots]
    if (header["sparse_slots"] != want_sparse
            or header["float_slots"] != want_float):
        raise ValueError(
            f"{path}: archive slots {header['sparse_slots']}/"
            f"{header['float_slots']} do not match schema "
            f"{want_sparse}/{want_float}")
    num = int(header["num"])
    float_widths = {s.name: s.max_len for s in schema.float_slots}
    arrays: dict[str, np.ndarray] = {}
    for col in header["columns"]:
        dt = np.dtype(col["dtype"])
        n = int(col["len"])
        group, _, name = col["name"].partition("/")
        if group == "float_values":
            want = num * float_widths[name]
            if n != want or dt != np.float32:
                raise ValueError(
                    f"{path}: float slot {name!r} was archived with "
                    f"{n // max(num, 1)} values/example "
                    f"({dt}), schema expects {float_widths[name]} "
                    "(float32) — stale archive?")
        arrays[col["name"]] = np.frombuffer(buf, dt, n, off).copy()
        off += n * dt.itemsize
    return SlotRecordBatch(
        schema=schema, num=num,
        sparse_values=[arrays[f"sparse_values/{n}"] for n in want_sparse],
        sparse_offsets=[arrays[f"sparse_offsets/{n}"] for n in want_sparse],
        float_values=[arrays[f"float_values/{n}"] for n in want_float],
        ins_id=arrays["ins_id"], search_id=arrays["search_id"],
        rank=arrays["rank"], cmatch=arrays["cmatch"],
    )


def archive_filelist(files: Sequence[str], schema: DataFeedSchema,
                     out_dir: str, **read_kw) -> list[str]:
    """Convert text files to archives (one .pbar per input), returning the
    new filelist — the 'pay parse once' preprocessing step."""
    from paddlebox_tpu.data.reader import read_file
    os.makedirs(out_dir, exist_ok=True)
    out: list[str] = []
    seen: set[str] = set()
    for path in files:
        batch = read_file(path, schema, **read_kw)
        name = os.path.basename(path) + ARCHIVE_SUFFIX
        if name in seen:
            raise ValueError(
                f"archive name collision: two inputs map to {name!r}")
        seen.add(name)
        dst = os.path.join(out_dir, name)
        write_archive(dst, batch)
        out.append(dst)
    return out
