"""Pass-scoped in-memory dataset — the PadBoxSlotDataset equivalent.

Reference (data_set.{h,cc}; class at data_set.h:348-474): a pass's worth of
``SlotRecord``s is downloaded+parsed by a thread pool, globally shuffled
across nodes, merged, key-extracted into the parameter server's feed-pass
agent, then sliced into per-device batch ranges for the trainers
(``PrepareTrain``). ``PreLoadIntoMemory``/``WaitPreLoadDone`` overlap the next
pass's ingest with the current pass's training (data_set.cc:1712-1786).

TPU-native changes: records are columnar (``SlotRecordBatch``), shuffle rides
host TCP over DCN (``shuffle.py``), and "key extraction into the PS agent"
becomes handing the pass's unique keys to the embedding engine's
``begin_pass`` working-set builder (see embedding/store.py).
"""

from __future__ import annotations

import concurrent.futures
import threading
from typing import Iterator, Sequence

import numpy as np

from paddlebox_tpu.config import flags
from paddlebox_tpu.data.reader import ParserPlugin, read_file
from paddlebox_tpu.data.schema import DataFeedSchema
from paddlebox_tpu.data.slot_record import PackedBatch, SlotRecordBatch, batch_iterator
from paddlebox_tpu.data.shuffle import LocalShuffler, RoutingMode, TcpShuffleService, route_records
from paddlebox_tpu.monitor import counter_add as stat_add


class SlotDataset:
    """One pass of training data, held columnar in host memory."""

    def __init__(self, schema: DataFeedSchema,
                 shuffle_service: TcpShuffleService | None = None,
                 seed: int = 0):
        self.schema = schema
        self.filelist: list[str] = []
        self.pipe_command: str | None = None
        self.parser_plugin: ParserPlugin | None = None
        self.with_ins_id = False
        self.records = None
        self.date: int | None = None
        self._preload: concurrent.futures.Future | None = None
        self._pool = None
        self._shuffler = LocalShuffler(seed)
        self._service = shuffle_service
        self._lock = threading.Lock()
        # per-device slices set by prepare_train
        self._shards: list[SlotRecordBatch] = []

    # every rebind of the record batch bumps a version counter so pass-
    # level caches keyed on dataset content (Trainer._preplan_capacity's
    # capacity memo) invalidate when records are swapped behind an
    # unchanged num_examples (ADVICE r4; auc_runner ablation rebinds)
    @property
    def records(self) -> SlotRecordBatch | None:
        return self._records

    @records.setter
    def records(self, value: SlotRecordBatch | None) -> None:
        self._records = value
        self._records_version = getattr(self, "_records_version", 0) + 1

    # ---- configuration (BoxPSDataset python API, dataset.py:1081-1191) ----

    def set_filelist(self, files: Sequence[str]) -> None:
        self.filelist = list(files)

    def set_pipe_command(self, cmd: str | None) -> None:
        self.pipe_command = cmd

    def set_parser_plugin(self, plugin: ParserPlugin | None) -> None:
        self.parser_plugin = plugin

    def set_date(self, date: int) -> None:
        """Reference BoxPSDataset.set_date (dataset.py:1101)."""
        self.date = date

    # ---- ingest (LoadIntoMemory, data_set.cc:1780) ----

    def load_into_memory(self, global_shuffle: bool = True,
                         routing: RoutingMode = "random") -> None:
        n_threads = min(flags.dataset_load_thread_num, max(1, len(self.filelist)))
        with concurrent.futures.ThreadPoolExecutor(n_threads) as pool:
            parts = list(pool.map(self._read_one, self.filelist))
        parts = [p for p in parts if p.num > 0]
        batch = (SlotRecordBatch.concat(parts) if parts
                 else SlotRecordBatch.empty(self.schema))
        if global_shuffle and batch.num > 0:
            batch = self._global_shuffle(batch, routing)
        # UnrollInstance hook (data_set.cc:2356, data_feed.cc:3304): like
        # the reference, unrolling is plugin-defined — a parser plugin may
        # carry an `unroll(SlotRecordBatch) -> SlotRecordBatch` attribute
        # (e.g. expanding PV-merged page views back into instances) applied
        # once after load/shuffle.
        unroll = getattr(self.parser_plugin, "unroll", None)
        if unroll is not None and batch.num > 0:
            batch = unroll(batch)
        # STAT_ADD counters, like data_feed's feasign stats (monitor.h:129)
        stat_add("dataset.records_loaded", batch.num)
        stat_add("dataset.feasigns_loaded",
                 float(sum(len(v) for v in batch.sparse_values)))
        with self._lock:
            self.records = batch

    def preload_into_memory(self, **kw) -> None:
        """Overlap next pass ingest with training (PreLoadIntoMemory,
        data_set.cc:1712)."""
        ex = concurrent.futures.ThreadPoolExecutor(1)
        self._preload = ex.submit(self.load_into_memory, **kw)
        ex.shutdown(wait=False)

    def wait_preload_done(self) -> None:
        if self._preload is not None:
            self._preload.result()
            self._preload = None

    def _read_one(self, path: str) -> SlotRecordBatch:
        return read_file(path, self.schema, pipe_command=self.pipe_command,
                         parser_plugin=self.parser_plugin,
                         with_ins_id=self.with_ins_id)

    def _global_shuffle(self, batch: SlotRecordBatch,
                        routing: RoutingMode) -> SlotRecordBatch:
        if self._service is None:
            return self._shuffler.shuffle(batch, routing)
        # random-mode routing draws from the PERSISTENT shuffle generator
        # so shuffle_state() checkpoints the routing decisions too (a
        # mid-pass resume replays identical destinations)
        routed = route_records(batch, self._service.world, routing,
                               rng=self._shuffler.rng)
        received = self._service.exchange(routed, self.schema)
        merged = (SlotRecordBatch.concat(received) if received
                  else SlotRecordBatch.empty(self.schema))
        return self._shuffler.shuffle(merged) if merged.num else merged

    # ---- in-memory transforms ----

    def local_shuffle(self) -> None:
        if self.records is not None and self.records.num:
            self.records = self._shuffler.shuffle(self.records)

    # ---- crash-recovery shuffle cursor (distributed/resilience.py) ----

    def shuffle_state(self) -> dict:
        """The shuffle RNG cursor: JSON-serializable bit-generator state.
        Recorded into pass snapshots (PassCheckpointer cursor) so a
        resumed rank replays the identical per-pass permutations — the
        state BEFORE a pass's draw reproduces that pass's order, the
        state after it produces the next pass's."""
        return self._shuffler.state_dict()

    def set_shuffle_state(self, state: dict) -> None:
        self._shuffler.load_state_dict(state)

    # ---- elastic world shrink (distributed/resilience.py, ISSUE 6) ----

    def member_shards(self, world_size: int) -> list[SlotRecordBatch]:
        """Deterministic per-member slices of the current records — the
        same round-robin split :meth:`prepare_train` uses, returned
        instead of stored. Every rank computes the identical partition
        from the identically-shuffled records, so after a rank loss the
        survivors know exactly which records the departed rank owned
        without ever having talked to it."""
        assert self.records is not None
        n = self.records.num
        return [self.records.select(np.arange(d, n, world_size))
                for d in range(world_size)]

    def reroute_records(self, batch: SlotRecordBatch, world_size: int
                        ) -> list[SlotRecordBatch | None]:
        """Cursor-preserving re-route of ``batch`` across ``world_size``
        survivors, drawing destinations from THE persistent shuffle
        generator (:meth:`shuffle_state`'s cursor). See
        :func:`paddlebox_tpu.data.shuffle.elastic_reroute` for the
        lockstep contract."""
        from paddlebox_tpu.data.shuffle import elastic_reroute
        return elastic_reroute(batch, world_size, self._shuffler.rng)

    def slots_shuffle(self, slot_names: Sequence[str], seed: int = 0) -> None:
        """Shuffle the values of the given sparse slots *across examples*
        (reference BoxPSDataset.slots_shuffle, dataset.py:1191 — used for
        feature-ablation evaluation)."""
        if self.records is None or self.records.num == 0:
            return
        rng = np.random.default_rng(seed)
        rec = self.records
        sparse_names = [s.name for s in self.schema.sparse_slots]
        # resolve every name BEFORE mutating: an unknown slot must not
        # leave records half-shuffled with no version bump below
        slot_idx = [sparse_names.index(name) for name in slot_names]
        for s in slot_idx:
            vals, offs = rec.sparse_values[s], rec.sparse_offsets[s]
            lens = offs[1:] - offs[:-1]
            # permute whole per-example value LISTS across examples (the
            # reference swaps slot value vectors between instances,
            # data_set.cc slots_shuffle) — example i receives example
            # perm[i]'s entire list, keeping multi-value lists intact
            perm = rng.permutation(rec.num)
            new_lens = lens[perm]
            new_offs = np.zeros(rec.num + 1, dtype=np.int64)
            np.cumsum(new_lens, out=new_offs[1:])
            total = int(new_offs[-1])
            # vectorized ragged gather: output position t inside example j
            # reads vals[offs[perm[j]] + (t - new_offs[j])]
            src_start = np.repeat(offs[:-1][perm], new_lens)
            local = np.arange(total, dtype=np.int64) - \
                np.repeat(new_offs[:-1], new_lens)
            rec.sparse_values[s] = vals[src_start + local]
            rec.sparse_offsets[s] = new_offs
        # in-place mutation changes per-example routing: pass-level caches
        # keyed on content (capacity-preplan memo) must invalidate
        self._records_version = getattr(self, "_records_version", 0) + 1

    def merge_by_ins_id(self, merge_size: int = 0) -> int:
        """Merge examples sharing an ins_id into one (MergeByInsId,
        reference data_set.cc:1012): sort by ins_id, group, and concatenate
        each group's sparse slot values member-by-member. With
        ``merge_size > 0``, groups whose size differs are DROPPED (the
        reference's strict mode — e.g. exactly one click log + one show
        log per instance). Float slots and metadata come from the group's
        first member. Returns the number of dropped examples."""
        assert self.records is not None
        r = self.records
        if r.num == 0:
            return 0
        if not r.ins_id.any():
            raise ValueError(
                "merge_by_ins_id needs real instance ids; load with "
                "with_ins_id=True (all ins_id are 0 — merging would "
                "collapse the whole dataset into one group)")
        order = np.argsort(r.ins_id, kind="stable")
        ids = r.ins_id[order]
        starts = np.flatnonzero(
            np.concatenate([[True], ids[1:] != ids[:-1]]))
        sizes = np.diff(np.append(starts, len(ids)))
        keep = (sizes == merge_size) if merge_size > 0 \
            else np.ones(len(starts), bool)
        dropped = int(sizes[~keep].sum())
        kept_groups = [(starts[g], sizes[g]) for g in np.flatnonzero(keep)]
        if not kept_groups:
            self.records = SlotRecordBatch.empty(self.schema)
            stat_add("dataset.merge_by_ins_id_dropped", dropped)
            return dropped
        # one ragged gather via select(), then collapse offsets at group
        # boundaries (offsets are cumulative, so the group's span is just
        # the offsets sampled at member boundaries)
        member_rows = np.concatenate(
            [order[st:st + sz] for st, sz in kept_groups])
        picked = r.select(member_rows)
        bounds = np.cumsum([0] + [sz for _, sz in kept_groups])
        firsts = r.select(np.asarray([order[st] for st, _ in kept_groups]))
        self.records = SlotRecordBatch(
            schema=r.schema, num=len(kept_groups),
            sparse_values=picked.sparse_values,
            sparse_offsets=[off[bounds] for off in picked.sparse_offsets],
            float_values=firsts.float_values,
            ins_id=firsts.ins_id, search_id=firsts.search_id,
            rank=firsts.rank, cmatch=firsts.cmatch)
        stat_add("dataset.merge_by_ins_id_dropped", dropped)
        return dropped

    def merge_by_search_id(self) -> np.ndarray:
        """Group examples into page views (PV merge, reference MergePvInstance):
        returns group ids per example ordered so same-search_id examples are
        adjacent; used to build rank_offset for rank_attention."""
        assert self.records is not None
        order = np.argsort(self.records.search_id, kind="stable")
        self.records = self.records.select(order)
        _, group = np.unique(self.records.search_id, return_inverse=True)
        return group

    # ---- hand-off to embedding engine + trainers ----

    def unique_keys(self) -> np.ndarray:
        """The pass's feature-sign working set (MergeInsKeys → PSAgent,
        data_set.cc:1786)."""
        assert self.records is not None
        return self.records.unique_keys()

    def prepare_train(self, num_shards: int) -> None:
        """Slice records round-robin into per-device shards
        (PadBoxSlotDataset::PrepareTrain, data_set.h:376)."""
        assert self.records is not None
        n = self.records.num
        self._shards = [
            self.records.select(np.arange(d, n, num_shards))
            for d in range(num_shards)
        ]

    def shard_batches(self, shard: int, batch_size: int | None = None,
                      drop_last: bool = True) -> Iterator[PackedBatch]:
        bs = batch_size or self.schema.batch_size
        return batch_iterator(self._shards[shard], bs, drop_last=drop_last)

    def batches(self, batch_size: int | None = None,
                drop_last: bool = True) -> Iterator[PackedBatch]:
        assert self.records is not None
        bs = batch_size or self.schema.batch_size
        return batch_iterator(self.records, bs, drop_last=drop_last)

    @property
    def num_examples(self) -> int:
        return 0 if self.records is None else self.records.num

    def release_memory(self) -> None:
        self.records = None
        self._shards = []
