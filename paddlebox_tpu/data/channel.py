"""Bounded MPMC channel.

Equivalent of the reference's ``ChannelObject`` (framework/channel.h) and
``BlockingQueue`` (operators/reader/blocking_queue.h): the concurrency
primitive the whole ingest pipeline is built from. Python-side we wrap
``queue.Queue`` with close semantics so consumers can drain-and-exit.
"""

from __future__ import annotations

import queue
import threading
from typing import Generic, Iterator, Optional, TypeVar

T = TypeVar("T")


class Channel(Generic[T]):
    _SENTINEL = object()

    def __init__(self, capacity: int = 0):
        self._q: queue.Queue = queue.Queue(maxsize=capacity)
        self._closed = threading.Event()

    def put(self, item: T) -> None:
        if self._closed.is_set():
            raise RuntimeError("put on closed channel")
        self._q.put(item)

    def get(self, timeout: Optional[float] = None) -> Optional[T]:
        """Blocking get; returns None when the channel is closed and drained."""
        while True:
            try:
                item = self._q.get(timeout=0.05 if self._closed.is_set() else timeout)
            except queue.Empty:
                if self._closed.is_set() and self._q.empty():
                    return None
                continue
            return item  # type: ignore[return-value]

    def close(self) -> None:
        self._closed.set()

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def __iter__(self) -> Iterator[T]:
        while True:
            item = self.get()
            if item is None:
                return
            yield item

    def qsize(self) -> int:
        return self._q.qsize()
