"""Inter-host global shuffle service.

Role of ``boxps::PaddleShuffler`` + ``PadBoxSlotDataConsumer`` +
``PadBoxSlotDataset::ShuffleData/ReceiveSuffleData`` in the reference
(data_set.cc:1393-1417, 1916-2045): every host routes each record to a
destination host by a routing key, serializes batches, and sends them over the
cluster's data-plane network; receivers append into their in-memory dataset.

TPU-native redesign: the shuffle rides the *DCN* (host network), not ICI — it
is pure host-side work. The transport is a small length-prefixed TCP protocol
(no brpc dependency); routing modes mirror the reference exactly
(data_set.cc:1934-1942): ``random`` / hash of ``ins_id`` / ``search_id``.
A ``LocalShuffler`` covers the single-host case and all unit tests.
"""

from __future__ import annotations

import io
import socket
import struct
import threading
from typing import Literal, Sequence

import numpy as np

from paddlebox_tpu.data.slot_record import SlotRecordBatch
from paddlebox_tpu.monitor import context as mon_ctx
from paddlebox_tpu.utils.hashing import hash64_array

RoutingMode = Literal["random", "ins_id", "search_id"]


def route_records(batch: SlotRecordBatch, world_size: int, mode: RoutingMode,
                  seed: int = 0, rng: np.random.Generator | None = None
                  ) -> list[SlotRecordBatch | None]:
    """Split a batch into per-destination sub-batches (reference
    ShuffleData's routing switch, data_set.cc:1934-1942).

    ``random`` routing draws from ``rng`` when given (a persistent,
    checkpointable generator — see :meth:`LocalShuffler.state_dict`) and
    falls back to a throwaway generator seeded with ``seed``. Mid-pass
    crash recovery snapshots that generator state so a resumed rank
    replays the identical routing decisions."""
    if world_size == 1:
        return [batch]
    if mode == "search_id":
        dest = (batch.search_id % np.uint64(world_size)).astype(np.int64)
    elif mode == "ins_id":
        dest = (hash64_array(batch.ins_id) % np.uint64(world_size)).astype(np.int64)
    else:
        rng = np.random.default_rng(seed) if rng is None else rng
        dest = rng.integers(0, world_size, size=batch.num)
    out: list[SlotRecordBatch | None] = []
    for r in range(world_size):
        idx = np.nonzero(dest == r)[0]
        out.append(batch.select(idx) if len(idx) else None)
    return out


# ---- serialization (BinaryArchive equivalent, data_feed.h:1536) ----

def serialize_batch(batch: SlotRecordBatch) -> bytes:
    buf = io.BytesIO()
    arrays: dict[str, np.ndarray] = {
        "ins_id": batch.ins_id, "search_id": batch.search_id,
        "rank": batch.rank, "cmatch": batch.cmatch,
        "num": np.asarray([batch.num], dtype=np.int64),
    }
    for i, (v, o) in enumerate(zip(batch.sparse_values, batch.sparse_offsets)):
        arrays[f"sv{i}"] = v
        arrays[f"so{i}"] = o
    for i, fv in enumerate(batch.float_values):
        arrays[f"fv{i}"] = fv
    np.savez(buf, **arrays)
    return buf.getvalue()


def deserialize_batch(data: bytes, schema) -> SlotRecordBatch:
    z = np.load(io.BytesIO(data))
    n_sparse = len(schema.sparse_slots)
    n_float = len(schema.float_slots)
    return SlotRecordBatch(
        schema=schema, num=int(z["num"][0]),
        sparse_values=[z[f"sv{i}"] for i in range(n_sparse)],
        sparse_offsets=[z[f"so{i}"] for i in range(n_sparse)],
        float_values=[z[f"fv{i}"] for i in range(n_float)],
        ins_id=z["ins_id"], search_id=z["search_id"],
        rank=z["rank"], cmatch=z["cmatch"],
    )


def elastic_reroute(batch: SlotRecordBatch, world_size: int,
                    rng: np.random.Generator
                    ) -> list[SlotRecordBatch | None]:
    """Re-partition a departed rank's unconsumed records across the
    surviving world (elastic shrink, distributed/resilience.py).

    This is ``route_records`` in random mode drawing from the PERSISTENT
    shuffle generator — the checkpointable cursor every rank restores to
    the same state. Because all survivors hold identical RNG state and
    call this with identical inputs in the same order, each computes the
    SAME destination assignment and simply keeps its own slice: the
    departed rank's records land on exactly one survivor each with no
    exchange traffic, and the generator advances identically everywhere
    (an empty batch draws nothing, and a world of one routes without
    drawing — both keep the cursor in lockstep)."""
    return route_records(batch, world_size, "random", rng=rng)


class LocalShuffler:
    """Single-host shuffle: a permutation. world_size == 1.

    The generator is persistent across passes, and its state is part of
    the crash-recovery dataset cursor: ``state_dict``/``load_state_dict``
    round-trip the bit-generator state (JSON-serializable), so a resumed
    rank draws the exact permutation sequence the killed run would have —
    mid-pass resume depends on replaying the SAME pass order.
    """

    def __init__(self, seed: int = 0):
        self.rng = np.random.default_rng(seed)

    def shuffle(self, batch: SlotRecordBatch, mode: RoutingMode = "random"
                ) -> SlotRecordBatch:
        return batch.shuffle(self.rng)

    def state_dict(self) -> dict:
        return self.rng.bit_generator.state

    def load_state_dict(self, state: dict) -> None:
        self.rng.bit_generator.state = state


class TcpShuffleService:
    """Peer-to-peer record exchange over TCP (one instance per host).

    Protocol: 8-byte big-endian length + npz payload per message; a zero
    length marks end-of-stream from that peer. ``exchange`` plays both sides:
    sends this host's routed sub-batches to every peer while a server thread
    collects sub-batches addressed here (the reference overlaps these with
    shuffle threads too, data_set.cc:1916-2045).
    """

    def __init__(self, rank: int, endpoints: Sequence[str]):
        self.rank = rank
        self.endpoints = list(endpoints)
        self.world = len(endpoints)
        host, port = self.endpoints[rank].rsplit(":", 1)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, int(port)))
        self._srv.listen(self.world)

    def exchange(self, outgoing: list[SlotRecordBatch | None], schema,
                 timeout: float = 120.0) -> list[SlotRecordBatch]:
        received: list[SlotRecordBatch] = []
        lock = threading.Lock()
        expected = self.world - 1
        done_peers = [0]

        def serve() -> None:
            while done_peers[0] < expected:
                conn, _ = self._srv.accept()
                with conn:
                    while True:
                        hdr = _recv_exact(conn, 8)
                        (ln,) = struct.unpack(">Q", hdr)
                        if ln == 0:
                            break
                        payload = _recv_exact(conn, ln)
                        b = deserialize_batch(payload, schema)
                        with lock:
                            received.append(b)
                done_peers[0] += 1

        server = mon_ctx.spawn(serve)
        server.start()
        for peer in range(self.world):
            if peer == self.rank:
                continue
            host, port = self.endpoints[peer].rsplit(":", 1)
            with socket.create_connection((host, int(port)), timeout=60) as s:
                sub = outgoing[peer]
                if sub is not None and sub.num > 0:
                    payload = serialize_batch(sub)
                    s.sendall(struct.pack(">Q", len(payload)) + payload)
                s.sendall(struct.pack(">Q", 0))
        server.join(timeout=timeout)
        if server.is_alive():
            # a slow/dead peer past the deadline means records are MISSING;
            # continuing would silently train on truncated data (reference
            # shuffle errors are fail-stop, data_set.cc:1393-1417)
            raise RuntimeError(
                f"global shuffle exchange timed out after {timeout:.0f}s: "
                f"received from {done_peers[0]}/{expected} peers")
        mine = outgoing[self.rank]
        if mine is not None and mine.num > 0:
            received.append(mine)
        return received

    def close(self) -> None:
        self._srv.close()


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        c = conn.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("peer closed mid-message")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)
