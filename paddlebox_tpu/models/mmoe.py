"""MMoE — multi-gate mixture-of-experts multi-task CTR tower.

Reference scope: SURVEY.md §7.6 (MMoE in the model-zoo milestone; the
reference runs MMoE-style models as plain dense towers — SURVEY.md §2.3
"Expert parallelism: absent"). Experts are small MLPs evaluated for every
example (one batched einsum over the expert axis — no routing sparsity, so
no load-balancing machinery needed at CTR expert counts); each task has a
softmax gate over experts and its own tower head.

`apply` returns the primary task's logits (trainer-compatible);
`apply_tasks` returns all task logits (B, num_tasks).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.nn import dense_init, mlp_apply, mlp_init
from paddlebox_tpu.ops import fused_seqpool_cvm


class MMoEModel:
    name = "mmoe"
    # pulled is consumed only through fused_seqpool_cvm*, so the
    # trainer may substitute the fused gather-pool pull (PooledSlots)
    pooled_pull_ok = True

    def __init__(self, num_slots: int, emb_dim: int, dense_dim: int = 0,
                 num_experts: int = 4, num_tasks: int = 2,
                 expert_hidden: tuple[int, ...] = (64,),
                 expert_out: int = 32,
                 tower_hidden: tuple[int, ...] = (32,),
                 use_cvm: bool = True, compute_dtype=jnp.float32):
        self.num_slots = num_slots
        self.emb_dim = emb_dim
        self.dense_dim = dense_dim
        self.num_experts = num_experts
        self.num_tasks = num_tasks
        self.expert_hidden = tuple(expert_hidden)
        self.expert_out = expert_out
        self.tower_hidden = tuple(tower_hidden)
        self.use_cvm = use_cvm
        self.compute_dtype = compute_dtype
        slot_feat = (3 + emb_dim) if use_cvm else (1 + emb_dim)
        self.in_dim = num_slots * slot_feat + dense_dim
        self.expert_dims = (self.in_dim, *expert_hidden, expert_out)
        self.tower_dims = (expert_out, *tower_hidden, 1)

    def init(self, key):
        ke, kg, kt = jax.random.split(key, 3)
        experts = [mlp_init(k, self.expert_dims)
                   for k in jax.random.split(ke, self.num_experts)]
        gates = [dense_init(k, self.in_dim, self.num_experts)
                 for k in jax.random.split(kg, self.num_tasks)]
        towers = [mlp_init(k, self.tower_dims)
                  for k in jax.random.split(kt, self.num_tasks)]
        return {"experts": experts, "gates": gates, "towers": towers}

    def _features(self, pulled, mask, dense, segment_ids):
        feats = fused_seqpool_cvm(pulled, mask, segment_ids, self.num_slots,
                                  use_cvm=self.use_cvm)
        return (jnp.concatenate([feats, dense], axis=1)
                if self.dense_dim else feats)

    def apply_tasks(self, params, pulled, mask, dense, segment_ids,
                    num_slots=None) -> jnp.ndarray:
        cd = self.compute_dtype
        x = self._features(pulled, mask, dense, segment_ids)
        expert_out = jnp.stack(
            [mlp_apply(e, x, final_activation="relu", compute_dtype=cd)
             for e in params["experts"]], axis=1)        # (B, E, O)
        logits = []
        for gate, tower in zip(params["gates"], params["towers"]):
            g = jax.nn.softmax(
                (jnp.asarray(x, cd) @ jnp.asarray(gate["w"], cd)
                 ).astype(jnp.float32) + gate["b"], axis=-1)  # (B, E)
            mixed = jnp.einsum("be,beo->bo", g, expert_out)
            logits.append(mlp_apply(tower, mixed, compute_dtype=cd)[:, 0])
        return jnp.stack(logits, axis=1)                 # (B, T)

    def apply(self, params, pulled, mask, dense, segment_ids, num_slots=None):
        return self.apply_tasks(params, pulled, mask, dense,
                                segment_ids, num_slots)[:, 0]
