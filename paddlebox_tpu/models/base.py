"""Model interface.

A CTR model consumes:
- ``pulled``  (B, T, P) raw pull values for all sparse tokens (P = show, clk,
  w, embedx — see embedding/config.py), with ``mask`` (B, T) and the static
  SparseLayout, and
- ``dense``   (B, F) float slot columns (label excluded),

and produces logits (B,). Models own their dense parameters; the embedding
table is the trainer's (it lives in the sharded working set). This mirrors
the reference's split: pull_box_sparse feeds slot tensors into a
fluid-layers graph while the table lives in BoxPS (SURVEY.md §3.2).
"""

from __future__ import annotations

from typing import Any, Protocol

import jax.numpy as jnp
import numpy as np


class CTRModel(Protocol):
    name: str

    def init(self, key) -> Any: ...

    def apply(self, params: Any, pulled: jnp.ndarray, mask: jnp.ndarray,
              dense: jnp.ndarray, segment_ids: np.ndarray,
              num_slots: int) -> jnp.ndarray: ...
