"""DNN-CTR: the Criteo-Kaggle baseline tower.

The reference's canonical slot-DNN (the model family behind
ctr_dataset_reader.py / dist_fleet_ctr.py tests): per-slot embeddings are
seqpool+CVM'd, concatenated with dense features, and fed through a ReLU MLP
to a sigmoid CTR head.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.models.nn import mlp_apply, mlp_init
from paddlebox_tpu.ops import fused_seqpool_cvm


class DNNCTRModel:
    name = "dnn_ctr"
    # pulled is consumed only through fused_seqpool_cvm*, so the
    # trainer may substitute the fused gather-pool pull (PooledSlots)
    pooled_pull_ok = True

    def __init__(self, num_slots: int, emb_dim: int, dense_dim: int = 0,
                 hidden: tuple[int, ...] = (512, 256, 128),
                 use_cvm: bool = True, compute_dtype=jnp.float32):
        self.num_slots = num_slots
        self.emb_dim = emb_dim
        self.dense_dim = dense_dim
        self.use_cvm = use_cvm
        self.hidden = tuple(hidden)
        self.compute_dtype = compute_dtype
        slot_feat = (3 + emb_dim) if use_cvm else (1 + emb_dim)
        self.in_dim = num_slots * slot_feat + dense_dim
        self.dims = (self.in_dim, *hidden, 1)

    def init(self, key):
        return {"mlp": mlp_init(key, self.dims)}

    def apply(self, params, pulled, mask, dense, segment_ids, num_slots=None):
        feats = fused_seqpool_cvm(pulled, mask, segment_ids,
                                  self.num_slots, use_cvm=self.use_cvm)
        x = (jnp.concatenate([feats, dense], axis=1)
             if self.dense_dim else feats)
        logits = mlp_apply(params["mlp"], x,
                           compute_dtype=self.compute_dtype)
        return logits[:, 0]
