"""DeepFM — the flagship benchmark model (BASELINE.md north star).

wide: per-feature scalar weight w summed per example (the embed_w column the
reference dedicates to exactly this role);
FM second order: 0.5 * ((Σ_s v_s)² - Σ_s v_s²) over slot embedding vectors
(sum-square trick);
deep: MLP over [CVM features, dense].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.models.nn import mlp_apply, mlp_init
from paddlebox_tpu.ops import fused_seqpool_cvm


class DeepFMModel:
    name = "deepfm"
    # pulled is consumed only through fused_seqpool_cvm*, so the
    # trainer may substitute the fused gather-pool pull (PooledSlots)
    pooled_pull_ok = True

    def __init__(self, num_slots: int, emb_dim: int, dense_dim: int = 0,
                 hidden: tuple[int, ...] = (400, 400, 400),
                 use_cvm: bool = True, compute_dtype=jnp.float32):
        self.num_slots = num_slots
        self.emb_dim = emb_dim
        self.dense_dim = dense_dim
        self.use_cvm = use_cvm
        self.hidden = tuple(hidden)
        self.compute_dtype = compute_dtype
        slot_feat = (3 + emb_dim) if use_cvm else (1 + emb_dim)
        self.deep_in = num_slots * slot_feat + dense_dim
        self.dims = (self.deep_in, *hidden, 1)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        params = {"mlp": mlp_init(k1, self.dims),
                  "bias": jnp.zeros((1,), jnp.float32)}
        if self.dense_dim:
            params["wide_dense"] = (
                jax.random.normal(k2, (self.dense_dim,), jnp.float32) * 0.01)
        return params

    def apply(self, params, pulled, mask, dense, segment_ids, num_slots=None):
        feats = fused_seqpool_cvm(pulled, mask, segment_ids, self.num_slots,
                                  use_cvm=self.use_cvm, flatten=False)
        # feats (B, S, slot_feat): [log show, log ctr, w, embedx] if cvm
        off = 2 if self.use_cvm else 0
        w = feats[..., off]                     # (B, S) summed scalar weights
        v = feats[..., off + 1:]                # (B, S, emb_dim)
        wide = jnp.sum(w, axis=1)
        sum_v = jnp.sum(v, axis=1)
        fm = 0.5 * jnp.sum(sum_v * sum_v - jnp.sum(v * v, axis=1), axis=1)
        x = feats.reshape(feats.shape[0], -1)
        if self.dense_dim:
            x = jnp.concatenate([x, dense], axis=1)
            wide = wide + dense @ params["wide_dense"]
        deep = mlp_apply(params["mlp"], x,
                         compute_dtype=self.compute_dtype)[:, 0]
        return wide + fm + deep + params["bias"][0]
