from paddlebox_tpu.models.dnn_ctr import DNNCTRModel  # noqa: F401
from paddlebox_tpu.models.deepfm import DeepFMModel  # noqa: F401
