from paddlebox_tpu.models.dnn_ctr import DNNCTRModel  # noqa: F401
from paddlebox_tpu.models.deepfm import DeepFMModel  # noqa: F401
from paddlebox_tpu.models.wide_deep import WideDeepModel  # noqa: F401
from paddlebox_tpu.models.dcn import DCNv2Model  # noqa: F401
from paddlebox_tpu.models.dlrm import DLRMModel  # noqa: F401
from paddlebox_tpu.models.mmoe import MMoEModel  # noqa: F401
from paddlebox_tpu.models.pv_rank import PVRankModel  # noqa: F401

MODEL_REGISTRY = {
    m.name: m for m in (DNNCTRModel, DeepFMModel, WideDeepModel,
                        DCNv2Model, DLRMModel, MMoEModel, PVRankModel)
}
