"""Minimal functional NN building blocks.

The reference's dense towers are static-graph ``fluid.layers.fc`` stacks
(python/paddle/fluid/layers); here parameters are plain pytrees (dicts of
arrays) built/applied by pure functions — no module framework needed, and
everything jits/shards transparently. bfloat16 compute is applied at the
matmul boundary (MXU-friendly) while params stay float32.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def dense_init(key, in_dim: int, out_dim: int, scale: str = "glorot"):
    if scale == "glorot":
        std = (2.0 / (in_dim + out_dim)) ** 0.5
    else:
        std = 0.01
    w = jax.random.normal(key, (in_dim, out_dim), jnp.float32) * std
    return {"w": w, "b": jnp.zeros((out_dim,), jnp.float32)}


def dense_apply(p, x: jnp.ndarray, activation: str | None = None,
                compute_dtype=jnp.float32) -> jnp.ndarray:
    y = jnp.asarray(x, compute_dtype) @ jnp.asarray(p["w"], compute_dtype)
    y = y.astype(jnp.float32) + p["b"]
    if activation == "relu":
        y = jnp.maximum(y, 0.0)
    elif activation == "tanh":
        y = jnp.tanh(y)
    elif activation is not None:
        raise ValueError(activation)
    return y


def mlp_init(key, dims: Sequence[int]):
    keys = jax.random.split(key, len(dims) - 1)
    return [dense_init(k, dims[i], dims[i + 1]) for i, k in enumerate(keys)]


def mlp_apply(layers, x: jnp.ndarray, final_activation: str | None = None,
              compute_dtype=jnp.float32) -> jnp.ndarray:
    for i, p in enumerate(layers):
        last = i == len(layers) - 1
        act = final_activation if last else "relu"
        x = dense_apply(p, x, activation=act, compute_dtype=compute_dtype)
    return x
