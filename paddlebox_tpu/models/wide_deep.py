"""Wide&Deep — linear wide part over per-slot scalar weights + deep MLP.

One of the stock CTR families the reference's fleet tests exercise
(dist_fleet_ctr.py model zoo lineage). The wide part consumes the dedicated
per-feature scalar weight column (the same `w` column DeepFM's first-order
term uses); the deep part consumes seqpool+CVM features and dense floats.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.nn import mlp_apply, mlp_init
from paddlebox_tpu.ops import fused_seqpool_cvm


class WideDeepModel:
    name = "wide_deep"
    # pulled is consumed only through fused_seqpool_cvm*, so the
    # trainer may substitute the fused gather-pool pull (PooledSlots)
    pooled_pull_ok = True

    def __init__(self, num_slots: int, emb_dim: int, dense_dim: int = 0,
                 hidden: tuple[int, ...] = (256, 128, 64),
                 use_cvm: bool = True, compute_dtype=jnp.float32):
        self.num_slots = num_slots
        self.emb_dim = emb_dim
        self.dense_dim = dense_dim
        self.use_cvm = use_cvm
        self.hidden = tuple(hidden)
        self.compute_dtype = compute_dtype
        slot_feat = (3 + emb_dim) if use_cvm else (1 + emb_dim)
        self.deep_in = num_slots * slot_feat + dense_dim
        self.dims = (self.deep_in, *hidden, 1)

    def init(self, key):
        k1, k2 = jax.random.split(key)
        params = {
            "mlp": mlp_init(k1, self.dims),
            # per-slot scale on the summed w column — the wide weights
            "wide_slot": jnp.ones((self.num_slots,), jnp.float32),
            "bias": jnp.zeros((1,), jnp.float32),
        }
        if self.dense_dim:
            params["wide_dense"] = (
                jax.random.normal(k2, (self.dense_dim,), jnp.float32) * 0.01)
        return params

    def apply(self, params, pulled, mask, dense, segment_ids, num_slots=None):
        feats = fused_seqpool_cvm(pulled, mask, segment_ids, self.num_slots,
                                  use_cvm=self.use_cvm, flatten=False)
        off = 2 if self.use_cvm else 0
        w = feats[..., off]                       # (B, S)
        wide = w @ params["wide_slot"]
        x = feats.reshape(feats.shape[0], -1)
        if self.dense_dim:
            x = jnp.concatenate([x, dense], axis=1)
            wide = wide + dense @ params["wide_dense"]
        deep = mlp_apply(params["mlp"], x,
                         compute_dtype=self.compute_dtype)[:, 0]
        return wide + deep + params["bias"][0]
