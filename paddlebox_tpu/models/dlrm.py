"""DLRM — bottom dense MLP + pairwise dot-product feature interactions.

Reference scope: SURVEY.md §7.6 ("DCN-v2/DLRM multi-hot"). Sparse slots are
sum-pooled (multi-hot → one vector per slot); the dense features pass
through a bottom MLP into the same embedding space; the interaction is the
upper triangle of the (S+1)×(S+1) Gram matrix of all vectors — one batched
matmul, MXU-friendly; top MLP over [dense_vec, interactions].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.nn import mlp_apply, mlp_init
from paddlebox_tpu.ops import fused_seqpool_cvm


class DLRMModel:
    name = "dlrm"
    # pulled is consumed only through fused_seqpool_cvm*, so the
    # trainer may substitute the fused gather-pool pull (PooledSlots)
    pooled_pull_ok = True

    def __init__(self, num_slots: int, emb_dim: int, dense_dim: int,
                 bottom_hidden: tuple[int, ...] = (64,),
                 top_hidden: tuple[int, ...] = (256, 128),
                 use_cvm: bool = False, compute_dtype=jnp.float32):
        self.num_slots = num_slots
        self.emb_dim = emb_dim
        self.dense_dim = dense_dim
        self.use_cvm = use_cvm
        self.bottom_hidden = tuple(bottom_hidden)
        self.top_hidden = tuple(top_hidden)
        self.compute_dtype = compute_dtype
        # bottom MLP maps dense floats → emb_dim so it joins the interaction
        self.bottom_dims = (max(dense_dim, 1), *bottom_hidden, emb_dim)
        n_vec = num_slots + 1
        n_pairs = n_vec * (n_vec - 1) // 2
        # top input carries the per-slot first-order w column too — the pull
        # layout dedicates it to exactly this role, and pure pairwise
        # interactions have no first-order path
        self.top_in = emb_dim + n_pairs + num_slots
        self.top_dims = (self.top_in, *top_hidden, 1)

    def init(self, key):
        kb, kt = jax.random.split(key)
        return {"bottom": mlp_init(kb, self.bottom_dims),
                "top": mlp_init(kt, self.top_dims)}

    def apply(self, params, pulled, mask, dense, segment_ids, num_slots=None):
        cd = self.compute_dtype
        feats = fused_seqpool_cvm(pulled, mask, segment_ids, self.num_slots,
                                  use_cvm=self.use_cvm, flatten=False)
        off = 3 if self.use_cvm else 1
        w = feats[..., off - 1]                           # (B, S) first-order
        v = feats[..., off:]                              # (B, S, E) pooled
        B = v.shape[0]
        if self.dense_dim:
            d_in = dense
        else:
            d_in = jnp.zeros((B, 1), jnp.float32)
        d_vec = mlp_apply(params["bottom"], d_in, final_activation="relu",
                          compute_dtype=cd)               # (B, E)
        allv = jnp.concatenate([d_vec[:, None, :], v], axis=1)  # (B, S+1, E)
        gram = jnp.einsum("bse,bte->bst", jnp.asarray(allv, cd),
                          jnp.asarray(allv, cd)).astype(jnp.float32)
        n = allv.shape[1]
        iu, ju = jnp.triu_indices(n, k=1)
        inter = gram[:, iu, ju]                           # (B, n_pairs)
        x = jnp.concatenate([d_vec, inter, w], axis=1)
        return mlp_apply(params["top"], x, compute_dtype=cd)[:, 0]
