"""PV (page-view) ad model: rank attention over same-PV peers.

The reference's ad-ranking path: the data feed merges a page view's ads
into one group (MergePvInstance / merge_by_search_id), builds the
``rank_offset`` matrix per batch (GetRankOffset, data_feed.h:1552-1706,
data_feed.cu:208 CopyRankOffsetKernel), and the model attends over the
pulled features of the OTHER ads in the same PV with rank-pair-specific
parameters (rank_attention_op.cu) plus per-slot unshared projections
(batch_fc_op.cu). This module is that model family end-to-end on TPU:

- per-slot unshared projection of the CVM slot features — ``batch_fc``
  with the slot axis as the group axis;
- ``rank_attention`` over same-PV peers;
- an MLP head over [slot features, attention output, dense].

Trainer integration: the model declares ``batch_extras`` — a host-side
hook the pack pipeline calls per batch (overlapped with device compute,
like every other host-side pack stage) to build rank_offset from the
batch's (rank, search_id) columns. Peer indices are built PER SHARD:
the batch axis shards contiguously across the mesh, so each shard's
attention peers must live on the same shard — PVs straddling a shard
boundary lose their cross-boundary peers (the reference keeps a PV on
one card for the same reason: pv_batch granularity, data_set.cc).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.models.nn import mlp_apply, mlp_init
from paddlebox_tpu.ops import fused_seqpool_cvm
from paddlebox_tpu.ops.batch_fc import batch_fc
from paddlebox_tpu.ops.rank_attention import build_rank_offset, rank_attention


class PVRankModel:
    name = "pv_rank"
    # pulled is consumed only through fused_seqpool_cvm*, so the
    # trainer may substitute the fused gather-pool pull (PooledSlots)
    pooled_pull_ok = True
    num_extras = 1      # rank_offset — staged by the trainer per batch

    def __init__(self, num_slots: int, emb_dim: int, dense_dim: int = 0,
                 hidden: tuple[int, ...] = (64, 32), max_rank: int = 3,
                 slot_proj: int = 8, att_dim: int = 8, use_cvm: bool = True):
        self.num_slots = num_slots
        self.emb_dim = emb_dim
        self.dense_dim = dense_dim
        self.hidden = tuple(hidden)
        self.max_rank = max_rank
        self.slot_proj = slot_proj
        self.att_dim = att_dim
        self.use_cvm = use_cvm
        self.slot_feat = (3 + emb_dim) if use_cvm else (1 + emb_dim)
        self.x_dim = num_slots * slot_proj
        self.dims = (self.x_dim + att_dim + dense_dim, *self.hidden, 1)

    def init(self, key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        S, C, d, K = (self.num_slots, self.slot_feat, self.slot_proj,
                      self.max_rank)
        return {
            "slot_w": jax.random.normal(k1, (S, C, d), jnp.float32)
            * (2.0 / (C + d)) ** 0.5,
            "slot_b": jnp.zeros((S, d), jnp.float32),
            "rank_param": jax.random.normal(
                k2, (K * K * self.x_dim, self.att_dim), jnp.float32) * 0.02,
            "mlp": mlp_init(k3, self.dims),
            "bias": jnp.zeros((1,), jnp.float32),
        }

    def batch_extras(self, pb, n_shards: int = 1) -> tuple[np.ndarray]:
        """Host-side pack stage: rank_offset with SHARD-LOCAL peer
        indices (one build per contiguous batch shard — see module
        docstring on PV/shard granularity)."""
        B = len(pb.rank)
        groups = (pb.search_id if pb.search_id is not None
                  else np.zeros(B, np.uint64))
        bl = B // n_shards
        parts = [build_rank_offset(pb.rank[s * bl:(s + 1) * bl],
                                   groups[s * bl:(s + 1) * bl],
                                   self.max_rank)
                 for s in range(n_shards)]
        return (np.concatenate(parts, axis=0),)

    def apply(self, params, pulled, mask, dense, segment_ids,
              num_slots=None, rank_offset=None):
        assert rank_offset is not None, (
            "PVRankModel needs the rank_offset extra (trainer stages it "
            "via batch_extras)")
        B = pulled.shape[0]
        feats = fused_seqpool_cvm(pulled, mask, segment_ids,
                                  self.num_slots, use_cvm=self.use_cvm,
                                  flatten=False)          # (B, S, C)
        # per-slot UNSHARED projection: slots are the batch_fc group axis
        proj = batch_fc(jnp.swapaxes(feats, 0, 1), params["slot_w"],
                        params["slot_b"], activation="relu")   # (S, B, d)
        x = jnp.swapaxes(proj, 0, 1).reshape(B, self.x_dim)
        att = rank_attention(x, rank_offset, params["rank_param"],
                             self.max_rank)               # (B, att_dim)
        h = jnp.concatenate([x, att, dense], axis=1) if self.dense_dim \
            else jnp.concatenate([x, att], axis=1)
        deep = mlp_apply(params["mlp"], h)[:, 0]
        return deep + params["bias"][0]
