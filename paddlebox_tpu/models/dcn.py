"""DCN-v2 — deep & cross network with full-matrix cross layers.

Reference scope: SURVEY.md §7.6 names DCN-v2 in the model-zoo milestone
(BASELINE.json configs). Cross layer: x_{l+1} = x0 ⊙ (W_l x_l + b_l) + x_l —
each layer is one (D, D) matmul, MXU-friendly; the deep tower runs in
parallel and both heads concatenate into the logit layer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddlebox_tpu.models.nn import dense_init, mlp_apply, mlp_init
from paddlebox_tpu.ops import fused_seqpool_cvm


class DCNv2Model:
    name = "dcn_v2"
    # pulled is consumed only through fused_seqpool_cvm*, so the
    # trainer may substitute the fused gather-pool pull (PooledSlots)
    pooled_pull_ok = True

    def __init__(self, num_slots: int, emb_dim: int, dense_dim: int = 0,
                 hidden: tuple[int, ...] = (256, 128),
                 num_cross_layers: int = 3, use_cvm: bool = True,
                 compute_dtype=jnp.float32):
        self.num_slots = num_slots
        self.emb_dim = emb_dim
        self.dense_dim = dense_dim
        self.use_cvm = use_cvm
        self.hidden = tuple(hidden)
        self.num_cross_layers = num_cross_layers
        self.compute_dtype = compute_dtype
        slot_feat = (3 + emb_dim) if use_cvm else (1 + emb_dim)
        self.in_dim = num_slots * slot_feat + dense_dim
        self.deep_dims = (self.in_dim, *hidden)
        self.head_in = self.in_dim + hidden[-1]

    def init(self, key):
        kc, kd, kh = jax.random.split(key, 3)
        cross = [dense_init(k, self.in_dim, self.in_dim)
                 for k in jax.random.split(kc, self.num_cross_layers)]
        return {
            "cross": cross,
            "deep": mlp_init(kd, self.deep_dims),
            "head": dense_init(kh, self.head_in, 1),
        }

    def apply(self, params, pulled, mask, dense, segment_ids, num_slots=None):
        feats = fused_seqpool_cvm(pulled, mask, segment_ids, self.num_slots,
                                  use_cvm=self.use_cvm)
        x0 = (jnp.concatenate([feats, dense], axis=1)
              if self.dense_dim else feats)
        cd = self.compute_dtype
        # cross tower
        x = x0
        for layer in params["cross"]:
            xw = (jnp.asarray(x, cd) @ jnp.asarray(layer["w"], cd)
                  ).astype(jnp.float32) + layer["b"]
            x = x0 * xw + x
        # deep tower (parallel structure)
        deep = mlp_apply(params["deep"], x0, final_activation="relu",
                         compute_dtype=cd)
        h = jnp.concatenate([x, deep], axis=1)
        logits = (jnp.asarray(h, cd) @ jnp.asarray(params["head"]["w"], cd)
                  ).astype(jnp.float32) + params["head"]["b"]
        return logits[:, 0]
