"""Predictor — the serving-side runner.

The reference's analysis+executor inference engine (paddle/fluid/inference,
api_impl.cc PaddlePredictor) loads a saved program and runs it per request;
graph optimization passes do the fusing. Here loading gives back a pure
apply function which jit compiles once per batch shape — XLA is the analysis
pass — and the embedding half of the model is a host-side ServingTable
lookup feeding the device step, exactly mirroring how training splits
pull (host/PS) from the dense net (device).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.data.schema import DataFeedSchema
from paddlebox_tpu.data.slot_record import PackedBatch, SparseLayout
from paddlebox_tpu.inference.export import load_inference_model
from paddlebox_tpu.inference.serving_table import ServingTable


def make_serving_fn(model: Any, segment_ids, num_slots: int):
    """The one serving forward: sigmoid(apply(...)), multi-task aware.

    Shared by Predictor and the StableHLO exporter so the Python path and
    the portable artifact cannot diverge."""
    apply = getattr(model, "apply_tasks", None) or model.apply

    def fwd(params, pulled, mask, dense):
        return jax.nn.sigmoid(
            apply(params, pulled, mask, dense, segment_ids, num_slots))

    return fwd


class Predictor:
    """Batch scorer over an exported model directory."""

    def __init__(self, model: Any, params: Any, table: ServingTable,
                 schema: DataFeedSchema, label_slot: str = "label"):
        self.model = model
        self.params = params
        self.table = table
        self.schema = schema
        self.label_slot = label_slot
        self.layout = SparseLayout.from_schema(schema)
        self._device_params = jax.device_put(params)
        self._fwd = jax.jit(make_serving_fn(
            model, self.layout.segment_ids, self.layout.num_slots))

    @classmethod
    def load(cls, path: str) -> "Predictor":
        model, params, table, schema, meta = load_inference_model(path)
        return cls(model, params, table, schema,
                   label_slot=meta.get("label_slot", "label"))

    def with_model(self, params: Any, table: ServingTable) -> "Predictor":
        """Shallow clone serving new params/table through the SAME jitted
        forward. The hot-swap server publishes a new model version every
        pass; rebuilding a Predictor would re-jit (and recompile at the
        first request of every version) — sharing ``_fwd`` keeps the XLA
        compile cache across swaps, so a swap never stalls the request
        path on a compile."""
        p = object.__new__(Predictor)
        p.model = self.model
        p.params = params
        p.table = table
        p.schema = self.schema
        p.label_slot = self.label_slot
        p.layout = self.layout
        p._device_params = jax.device_put(params)
        p._fwd = self._fwd
        return p

    # ------------------------------------------------------------------
    def predict(self, ids: np.ndarray, mask: np.ndarray,
                dense: np.ndarray | None = None) -> np.ndarray:
        """ids uint64 (B, T) raw feature signs, mask bool (B, T),
        dense float32 (B, F) — returns probabilities (B,) (or (B, tasks)
        for multi-task models)."""
        ids = np.asarray(ids)
        mask = np.asarray(mask, bool)
        if ids.shape[1] != self.layout.total_len:
            raise ValueError(f"ids token axis {ids.shape[1]} != schema "
                             f"T={self.layout.total_len}")
        pulled = self.table.lookup(ids, mask)
        if dense is None:
            dense = np.zeros((ids.shape[0], 0), np.float32)
        out = self._fwd(self._device_params, jnp.asarray(pulled),
                        jnp.asarray(mask), jnp.asarray(dense, jnp.float32))
        return np.asarray(out)

    def predict_batch(self, pb: PackedBatch) -> np.ndarray:
        """Score a PackedBatch from the data pipeline; the label column
        (if present in the schema) is dropped from the float features."""
        lc, lw, _ = pb.schema.float_split_cols(self.label_slot)
        floats = pb.floats
        if lc >= 0:
            floats = np.concatenate([floats[:, :lc], floats[:, lc + lw:]],
                                    axis=1)
        return self.predict(pb.ids.astype(np.uint64), pb.mask, floats)
