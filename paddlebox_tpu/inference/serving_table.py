"""Read-optimized serving embedding table.

The reference serves sparse models from the "xbox" plane of a BoxPS
checkpoint: a key→pull-value map shipped to serving hosts, updated online by
delta models (SaveBase/SaveDelta, box_wrapper.cc:1387-1420; day/pass delta
layout fleet_util.py:722-745). Here that plane is an explicit host-side
structure: a sorted uint64 key array plus a dense (N, pull_width) float32
value matrix, so batched lookups are one ``np.searchsorted`` + gather —
no Python dict in the hot path. Unknown keys resolve to zeros
(FLAGS_enable_pull_box_padding_zero semantics, flags.cc:607).
"""

from __future__ import annotations

import json
import os

import numpy as np

from paddlebox_tpu.embedding.gating import GateSpec, gate_pull_xp


class ServingTable:
    def __init__(self, keys: np.ndarray, vals: np.ndarray,
                 gate: GateSpec | None = None):
        keys = np.asarray(keys, dtype=np.uint64)
        vals = np.asarray(vals, dtype=np.float32)
        if keys.ndim != 1 or vals.ndim != 2 or len(keys) != len(vals):
            raise ValueError("keys (N,) and vals (N, P) must align")
        order = np.argsort(keys, kind="stable")
        self.keys = keys[order]
        self.vals = vals[order]
        # Variable/NNCross presence gating (gating.py) — serving must mask
        # absent planes exactly like training pulls, or models see
        # train/serve skew on below-threshold keys
        self.gate = gate
        if len(self.keys) and (self.keys[1:] == self.keys[:-1]).any():
            # name the offenders: "duplicate keys" without WHICH keys sends
            # the operator diffing two multi-million-row exports by hand
            dup = np.unique(self.keys[1:][self.keys[1:] == self.keys[:-1]])
            shown = ", ".join(str(int(k)) for k in dup[:8])
            more = f", … +{len(dup) - 8} more" if len(dup) > 8 else ""
            raise ValueError(
                f"duplicate keys in serving table: {len(dup)} key(s) "
                f"appear more than once ({shown}{more})")

    # ------------------------------------------------------------------
    @property
    def pull_width(self) -> int:
        return self.vals.shape[1]

    def __len__(self) -> int:
        return len(self.keys)

    @classmethod
    def from_store(cls, store) -> "ServingTable":
        """Freeze a HostEmbeddingStore's pull plane for serving."""
        keys, vals = store.export_serving()
        return cls(keys, vals, gate=GateSpec.from_cfg(store.cfg))

    def copy(self) -> "ServingTable":
        """Deep copy for copy-on-write delta application: the hot-swap
        server builds the NEXT version's table by copying the live one and
        merging the delta into the copy, while the live table keeps
        serving in-flight requests untouched."""
        t = object.__new__(ServingTable)   # keys already sorted + deduped
        t.keys = self.keys.copy()
        t.vals = self.vals.copy()
        t.gate = self.gate
        return t

    # ------------------------------------------------------------------
    def _probe(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Sorted-array probe: → (clamped positions, hit mask)."""
        pos = np.searchsorted(self.keys, keys)
        pos_c = np.minimum(pos, max(len(self.keys) - 1, 0))
        hit = (self.keys[pos_c] == keys) if len(self.keys) else \
            np.zeros(len(keys), bool)
        return pos_c, hit

    def lookup(self, ids: np.ndarray, mask: np.ndarray | None = None
               ) -> np.ndarray:
        """ids uint64 (...,) → pull values (..., P); misses/masked → 0."""
        ids = np.asarray(ids, dtype=np.uint64)
        flat = ids.reshape(-1)
        pos_c, hit = self._probe(flat)
        if len(self.keys):
            out = np.where(hit[:, None], self.vals[pos_c], 0.0)
        else:
            out = np.zeros((len(flat), self.pull_width), np.float32)
        out = out.reshape(*ids.shape, self.pull_width)
        if self.gate is not None:
            out = gate_pull_xp(out, self.gate, np)
        if mask is not None:
            out = out * np.asarray(mask, np.float32)[..., None]
        return out.astype(np.float32)

    # ------------------------------------------------------------------
    def _merge(self, keys: np.ndarray, vals: np.ndarray) -> None:
        """Upsert rows (delta-model application, newest wins)."""
        keys = np.asarray(keys, dtype=np.uint64)
        vals = np.asarray(vals, dtype=np.float32)[:, :self.pull_width]
        # de-dup within the delta itself, keeping the last occurrence
        _, last = np.unique(keys[::-1], return_index=True)
        keep = len(keys) - 1 - last
        keys, vals = keys[keep], vals[keep]
        pos_c, exists = self._probe(keys)
        if exists.any():
            self.vals[pos_c[exists]] = vals[exists]
        if (~exists).any():
            all_keys = np.concatenate([self.keys, keys[~exists]])
            all_vals = np.concatenate([self.vals, vals[~exists]])
            order = np.argsort(all_keys, kind="stable")
            self.keys, self.vals = all_keys[order], all_vals[order]

    def _drop(self, keys: np.ndarray) -> None:
        keys = np.asarray(keys, dtype=np.uint64)
        if not len(keys) or not len(self.keys):
            return
        pos_c, hit = self._probe(keys)
        hits = pos_c[hit]
        if len(hits):
            keep = np.ones(len(self.keys), bool)
            keep[hits] = False
            self.keys, self.vals = self.keys[keep], self.vals[keep]

    def apply_delta_file(self, fname: str) -> None:
        """Apply one delta-*.npz written by HostEmbeddingStore.save_delta
        (rows arrive at full row_width; the serving table keeps only the
        pull columns) or by ServingTable.save."""
        z = np.load(fname)
        self._merge(z["keys"], z["rows"])
        if "removed" in z and len(z["removed"]):
            self._drop(z["removed"])

    def apply_delta_dir(self, path: str) -> int:
        """Apply every delta-*.npz under `path` in sequence order."""
        names = sorted(f for f in os.listdir(path) if f.startswith("delta-"))
        for f in names:
            self.apply_delta_file(os.path.join(path, f))
        return len(names)

    # ------------------------------------------------------------------
    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        fname = os.path.join(path, "serving.npz")
        # UNCOMPRESSED on purpose: stored zip members are plain .npy
        # bytes at fixed offsets, so any-language clients mmap the key
        # and value arrays directly (native/serving_score.c proves the
        # format; the reference ships Go/R clients for its xbox plane)
        np.savez(fname, keys=self.keys, rows=self.vals)
        meta = {"num_keys": int(len(self.keys)),
                "pull_width": int(self.pull_width)}
        if self.gate is not None:
            meta["gate"] = list(self.gate)
        with open(os.path.join(path, "serving_meta.json"), "w") as f:
            json.dump(meta, f)
        return fname

    @classmethod
    def load(cls, path: str) -> "ServingTable":
        z = np.load(os.path.join(path, "serving.npz"))
        gate = None
        meta_path = os.path.join(path, "serving_meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                g = json.load(f).get("gate")
            if g is not None:
                gate = GateSpec(int(g[0]), int(g[1]), float(g[2]),
                                float(g[3]))
        return cls(z["keys"], z["rows"], gate=gate)
