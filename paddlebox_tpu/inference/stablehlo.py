"""Portable compiled-model artifact via StableHLO.

The reference reaches non-Python serving through language clients around its
C++ inference engine (go/paddle/predictor.go, r/example). The TPU-native
equivalent is ``jax.export``: the dense half of the model (everything after
the embedding pull) is serialized as versioned StableHLO that any XLA
runtime — C++, TF serving, IFRT — can load and execute without Python.
The host half (ServingTable lookup) stays a trivial sorted-array gather that
any language can implement against serving.npz.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from paddlebox_tpu.data.schema import DataFeedSchema
from paddlebox_tpu.data.slot_record import SparseLayout
from paddlebox_tpu.utils import checkpoint as ckpt_lib
from paddlebox_tpu.inference.predictor import make_serving_fn


def export_stablehlo(path: str, model: Any, params: Any,
                     schema: DataFeedSchema, batch_size: int,
                     pull_width: int, num_dense: int | None = None,
                     label_slot: str = "label") -> str:
    """Serialize sigmoid(model.apply(params, …)) at a fixed batch size.

    Params are baked into the artifact as constants (a serving snapshot,
    like the reference's frozen inference program). Inputs:
        pulled (B, T, P) f32, mask (B, T) bool, dense (B, F) f32
    Returns the artifact file path.
    """
    layout = SparseLayout.from_schema(schema)
    seg, num_slots = layout.segment_ids, layout.num_slots
    if num_dense is None:
        _, lw, total = schema.float_split_cols(label_slot)
        num_dense = total - lw
    multi_task = hasattr(model, "apply_tasks")
    frozen = jax.device_put(params)
    serve = make_serving_fn(model, seg, num_slots)

    def fwd(pulled, mask, dense):
        return serve(frozen, pulled, mask, dense)

    B, T = batch_size, layout.total_len
    args = (
        jax.ShapeDtypeStruct((B, T, pull_width), jnp.float32),
        jax.ShapeDtypeStruct((B, T), jnp.bool_),
        jax.ShapeDtypeStruct((B, num_dense), jnp.float32),
    )
    exported = jax_export.export(jax.jit(fwd))(*args)
    os.makedirs(path, exist_ok=True)
    # Each file commits atomically (no torn bytes under a final name).
    # Two files can still pair across exports if a crash lands between
    # the replaces, so the meta carries the module's CRC32: the module
    # commits FIRST, the meta naming it second — a crash between them
    # leaves old meta + new module, which the loader detects by CRC
    # mismatch and rejects with a named error instead of compiling the
    # new module against the old static shapes.
    payload = exported.serialize()
    fname = os.path.join(path, "model.stablehlo")
    with ckpt_lib.atomic_file(fname) as tmp:
        with open(tmp, "wb") as f:
            f.write(payload)
    with ckpt_lib.atomic_file(os.path.join(path,
                                           "stablehlo_meta.json")) as tmp:
        with open(tmp, "w") as f:
            json.dump({"batch_size": B, "total_len": T,
                       "pull_width": pull_width, "num_dense": num_dense,
                       "multi_task": multi_task,
                       "module_crc32": zlib.crc32(payload) & 0xFFFFFFFF},
                      f)
    return fname


def load_stablehlo(path: str):
    """Reload the artifact → callable(pulled, mask, dense) -> probs.

    Rejects a module/meta pair from DIFFERENT exports (crash between the
    two commits): the meta's ``module_crc32`` must match the module
    bytes. Pre-CRC metas (older exports) load without the check."""
    with open(os.path.join(path, "model.stablehlo"), "rb") as f:
        raw = f.read()
    meta_path = os.path.join(path, "stablehlo_meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        want = meta.get("module_crc32")
        got = zlib.crc32(raw) & 0xFFFFFFFF
        if want is not None and int(want) != got:
            raise ckpt_lib.CheckpointCorruptError(
                meta_path,
                f"stablehlo module/meta pair mismatch (meta names crc "
                f"{want}, module bytes hash {got}) — torn export; "
                "re-export to re-pair")
    exported = jax_export.deserialize(raw)
    fn = jax.jit(exported.call)  # compile once; serving calls hit the cache

    def call(pulled, mask, dense):
        return np.asarray(fn(
            jnp.asarray(pulled, jnp.float32), jnp.asarray(mask, bool),
            jnp.asarray(dense, jnp.float32)))

    return call
