"""Inference-model export/load.

The reference's ``save_inference_model`` (python/paddle/fluid/io.py) writes a
pruned static-graph ProgramDesc + persistables that its inference engine
(paddle/fluid/inference) reloads in C++/Go/R clients. There is no graph
program to prune here — the jitted apply IS the graph — so an exported model
is a directory of plain artifacts:

    model.json    model name + constructor config + schema + format version
    dense.npz     trained dense parameters (flat pytree)
    serving.npz   frozen embedding pull plane (ServingTable)

``load_inference_model`` reconstructs the model from MODEL_REGISTRY and
returns everything a Predictor needs. For native/out-of-Python serving, see
stablehlo.py (the portable compiled artifact).
"""

from __future__ import annotations

import dataclasses
import inspect
import json
import os
from typing import Any

import jax
import jax.numpy as jnp

from paddlebox_tpu.data.schema import DataFeedSchema, Slot, SlotType
from paddlebox_tpu.inference.serving_table import ServingTable
from paddlebox_tpu.models import MODEL_REGISTRY
from paddlebox_tpu.utils import checkpoint

FORMAT_VERSION = 1


def model_config(model: Any) -> dict[str, Any]:
    """Recover a model's constructor kwargs by introspection.

    Every zoo model stores each __init__ arg under the same attribute name
    (models/*.py); custom models must follow the same convention (or ship
    their own export path).
    """
    sig = inspect.signature(type(model).__init__)
    cfg = {}
    for name in sig.parameters:
        if name == "self":
            continue
        if not hasattr(model, name):
            raise ValueError(
                f"{type(model).__name__} does not store __init__ arg "
                f"{name!r} as an attribute; cannot export its config")
        v = getattr(model, name)
        if name == "compute_dtype":
            v = jnp.dtype(v).name
        elif isinstance(v, tuple):
            v = list(v)
        cfg[name] = v
    return cfg


def _schema_json(schema: DataFeedSchema) -> dict[str, Any]:
    return {
        "batch_size": schema.batch_size,
        "slots": [{"name": s.name, "type": s.type.value,
                   "is_dense": s.is_dense, "is_used": s.is_used,
                   "max_len": s.max_len} for s in schema.slots],
    }


def _schema_from_json(d: dict[str, Any]) -> DataFeedSchema:
    slots = [Slot(s["name"], SlotType(s["type"]), s["is_dense"],
                  s["is_used"], s["max_len"]) for s in d["slots"]]
    return DataFeedSchema(slots, batch_size=d["batch_size"])


def save_inference_model(path: str, model: Any, params: Any,
                         store_or_table: Any, schema: DataFeedSchema,
                         label_slot: str = "label") -> str:
    """Write a self-contained serving directory; returns `path`.

    `store_or_table` is a HostEmbeddingStore (frozen via export_serving) or
    an already-built ServingTable.
    """
    if model.name not in MODEL_REGISTRY:
        raise ValueError(f"model {model.name!r} not in MODEL_REGISTRY")
    os.makedirs(path, exist_ok=True)
    table = (store_or_table if isinstance(store_or_table, ServingTable)
             else ServingTable.from_store(store_or_table))
    table.save(path)
    # uncompressed: mmap-able by non-Python clients (serving_score.c)
    checkpoint.save_pytree(params, os.path.join(path, "dense.npz"),
                           compress=False)
    meta = {
        "format_version": FORMAT_VERSION,
        "model": model.name,
        "config": model_config(model),
        "schema": _schema_json(schema),
        "label_slot": label_slot,
        "pull_width": table.pull_width,
    }
    with open(os.path.join(path, "model.json"), "w") as f:
        json.dump(meta, f, indent=1)
    return path


def load_inference_model(path: str):
    """→ (model, params, ServingTable, schema, meta)."""
    with open(os.path.join(path, "model.json")) as f:
        meta = json.load(f)
    if meta["format_version"] > FORMAT_VERSION:
        raise ValueError(f"export format {meta['format_version']} is newer "
                         f"than this framework understands")
    cls = MODEL_REGISTRY[meta["model"]]
    cfg = dict(meta["config"])
    if "compute_dtype" in cfg:
        cfg["compute_dtype"] = jnp.dtype(cfg["compute_dtype"])
    for k, v in cfg.items():
        if isinstance(v, list):
            cfg[k] = tuple(v)
    model = cls(**cfg)
    template = model.init(jax.random.PRNGKey(0))
    params = checkpoint.load_pytree(template, os.path.join(path, "dense.npz"))
    table = ServingTable.load(path)
    schema = _schema_from_json(meta["schema"])
    return model, params, table, schema, meta
