from paddlebox_tpu.inference.serving_table import ServingTable  # noqa: F401
from paddlebox_tpu.inference.export import (  # noqa: F401
    save_inference_model, load_inference_model, model_config)
from paddlebox_tpu.inference.predictor import Predictor  # noqa: F401
from paddlebox_tpu.inference.stablehlo import (  # noqa: F401
    export_stablehlo, load_stablehlo)
