"""Pass/day lifecycle façade — the BoxWrapper/BoxHelper singleton surface.

Reference (box_wrapper.h:419-424, 487-494, 625; pybind box_helper_py.cc:40-110):
the user-facing lifecycle is

    dataset.set_date(d)        → BoxHelper::SetDate
    dataset.begin_pass()       → BoxWrapper::BeginPass
    exe.train_from_dataset(..) → hot loop (§3.1), join/update FlipPhase
    dataset.end_pass(save)     → BoxWrapper::EndPass
    box.save_base/save_delta   → sparse checkpoint planes

Here the singleton owns the host embedding store, the metric registry, and
the phase bit; `Trainer.train_pass` does the per-pass HBM working-set build
(BeginFeedPass/EndFeedPass equivalent) internally, so BeginPass/EndPass at
this level is bookkeeping + persistence policy, which matches the reference's
split of labor between BoxHelper (data) and BoxPS (table).
"""

from __future__ import annotations

import time
from typing import Any

from paddlebox_tpu import monitor
from paddlebox_tpu.embedding import HostEmbeddingStore, tiering
from paddlebox_tpu.metrics.metric import MetricRegistry

JOIN_PHASE = 1
UPDATE_PHASE = 0


class BoxPS:
    """Owns the sparse store + metrics + pass/phase state for one job."""

    def __init__(self, store: HostEmbeddingStore,
                 metrics: MetricRegistry | None = None):
        self.store = store
        self.metrics = metrics or MetricRegistry()
        self.metrics.phase = JOIN_PHASE
        self.date: int | None = None
        self.pass_id = 0
        self.in_pass = False
        self._pass_t0 = 0.0
        # multi-host lifecycle (attach_collectives): lockstep barriers at
        # the pass boundaries + the heartbeat/watchdog pair
        self._col = None
        self._heartbeat = None

    # ---- multi-host lifecycle (ISSUE 5) ----

    def attach_collectives(self, collectives, heartbeat=None) -> None:
        """Make the pass lifecycle world-synchronous: ``begin_pass`` and
        ``end_pass`` barrier over the rendezvous store so no rank trains a
        pass the world has not entered (the reference's MPICluster barrier
        around BeginPass/EndPass, box_wrapper.h:415). With a
        ``HeartbeatMonitor``, the barriers poll its watchdog — a dead or
        stalled peer surfaces as a named-rank PeerLost/PeerStalled error
        instead of the bare store timeout — and each boundary publishes a
        fresh heartbeat so peers see this rank's pass progress
        immediately.

        Re-attachable: after an elastic world re-formation the driver (or
        ``Trainer.recover_world``) attaches the NEW generation's
        collectives + heartbeat — pass barriers then ride the new
        generation's store namespace, so a fenced straggler's stale
        arrivals can never satisfy them."""
        self._col = collectives
        self._heartbeat = heartbeat
        if heartbeat is not None and getattr(collectives, "watchdog",
                                             None) is None:
            collectives.watchdog = heartbeat

    def abort_pass(self, reason: str = "") -> None:
        """Close an open pass WITHOUT the end-of-pass snapshot/barrier —
        the elastic drain path: a peer failure unwound the step loop
        mid-pass, the world is about to re-form, and the normal
        ``end_pass`` barrier would hang on the dead rank. Safe when no
        pass is open (no-op). The telemetry pass scope is aborted so the
        flight record is not committed for a half-trained pass."""
        if not self.in_pass:
            return
        self.in_pass = False
        monitor.hub().abort_pass(reason=reason or "pass aborted")
        monitor.event("pass_aborted", pass_id=int(self.pass_id),
                      reason=reason[:200])

    @property
    def phase(self) -> int:
        """Single source of truth lives in the metric registry, which gates
        accumulation by phase."""
        return self.metrics.phase

    # ---- lifecycle (box_wrapper.h:419-424) ----

    def set_date(self, date: int) -> None:
        self.date = int(date)

    def begin_pass(self) -> None:
        if self.in_pass:
            raise RuntimeError("begin_pass while a pass is open")
        if self._col is not None:
            # lockstep: no rank opens pass N+1 until the world is ready
            self._col.barrier("begin_pass")
        self.in_pass = True
        self.pass_id += 1
        self._pass_t0 = time.time()
        # telemetry pass scope: everything until end_pass — trainer steps,
        # worker threads, checkpoint commits — is tagged with this pass
        monitor.hub().begin_pass(self.pass_id, phase=self.phase)
        if self._heartbeat is not None:
            self._heartbeat.publish()     # peers see the new pass at once

    def end_pass(self, need_save_delta: bool = False,
                 delta_path: str | None = None,
                 checkpointer=None, trainer=None,
                 dataset=None, publisher=None) -> dict[str, Any]:
        """Close the pass; optionally snapshot the delta plane
        (BoxPSDataset.end_pass(need_save_delta), dataset.py:1124).

        With ``checkpointer`` (a PassCheckpointer) + ``trainer``, commits
        the full crash-safe pass snapshot instead: dense + optimizer +
        sparse base-or-delta + metrics + cursor, atomically manifested —
        the need_save_delta flow upgraded to a resumable one. ``dataset``
        additionally records the shuffle RNG cursor
        (SlotDataset.shuffle_state) so a resumed rank draws the identical
        next-pass permutation. With attached collectives the snapshot is
        followed by a world barrier: no rank starts the next pass before
        every rank's snapshot committed (the election's common prefix
        stays one pass deep at most).

        ``publisher`` (a serving.ServingPublisher, requires ``trainer``)
        ships this pass's model to the serving plane — the reference's
        per-pass xbox delta (SaveDelta → donefile → ad servers). Publish
        runs AFTER the crash-safe snapshot; a publish failure degrades
        (warn + telemetry, serving keeps its last good version) instead
        of killing the pass loop — training is the producer, and the
        serving side's staleness reporting is the alarm."""
        if not self.in_pass:
            raise RuntimeError("end_pass without begin_pass")
        self.in_pass = False
        out: dict[str, Any] = {"pass_id": self.pass_id,
                               "seconds": time.time() - self._pass_t0}
        if checkpointer is not None:
            if trainer is None:
                raise ValueError("end_pass(checkpointer=...) needs trainer")
            shuffle_state = (dataset.shuffle_state()
                             if dataset is not None
                             and hasattr(dataset, "shuffle_state")
                             else None)
            out["snapshot"] = checkpointer.save(trainer, box=self,
                                                metrics=self.metrics,
                                                shuffle_state=shuffle_state)
        if need_save_delta:
            if delta_path is None:
                raise ValueError("need_save_delta requires delta_path")
            out["delta_file"] = self.store.save_delta(
                delta_path, pass_id=self.pass_id)
        if publisher is not None:
            if trainer is None:
                raise ValueError("end_pass(publisher=...) needs trainer "
                                 "(the dense params to publish)")
            try:
                out["publish"] = publisher.publish(
                    self.store, trainer.eval_params(),
                    pass_id=self.pass_id)
            except Exception as e:   # noqa: BLE001 — degrade, don't die
                import warnings
                out["publish"] = {"error": repr(e)}
                monitor.counter_add("serving.publish_failures")
                monitor.event("serving_publish_failed",
                              pass_id=int(self.pass_id),
                              error=repr(e)[:300])
                warnings.warn(f"serving publish failed for pass "
                              f"{self.pass_id} ({e!r}); serving stays on "
                              f"its last good version")
        # pass-boundary tier re-evaluation: spill-backed stores re-score
        # their RAM hot tier off this pass's observed per-row traffic
        # (embedding/tiering.py) — BEFORE the flight-record commit so the
        # tiering.* counter deltas land in this pass's stats_delta
        tier = tiering.end_pass_rebalance(self.store)
        if tier is not None:
            out["tiering"] = tier
        # HBM replica-tier refresh (flags.use_replica_cache): rebuilt off
        # the ranking the rebalance above just re-scored, and BEFORE the
        # flight-record commit so the pass's replica-hit delta lands in
        # this pass's stats_delta
        if trainer is not None and hasattr(trainer,
                                           "refresh_replica_boundary"):
            trainer.refresh_replica_boundary()
        # pass-boundary exchange-wire adaptation (flags.exchange_adaptive):
        # fleet-driven scopes adapt here, mirroring the tier re-eval —
        # BEFORE the flight-record commit so the decision (and any
        # exchange_wire_adapted event) lands in this pass's record
        if trainer is not None and hasattr(trainer, "adapt_wire_boundary"):
            wire_next = trainer.adapt_wire_boundary()
            if wire_next is not None:
                out["exchange_wire_next"] = wire_next
        # self-healing boundary (flags.self_healing): the remediation
        # loop consumes the live doctor findings and applies at most one
        # guarded action — BEFORE the flight-record commit so the
        # remediation record + before-deltas land in this pass's record
        if trainer is not None and hasattr(trainer, "remediation_boundary"):
            healed = trainer.remediation_boundary()
            if healed is not None:
                out["remediation"] = healed
        # flight-record commit LAST: checkpoint/delta durations and bytes
        # above land in this pass's stats_delta and event stream
        out["flight_record"] = monitor.hub().end_pass(metrics=self.metrics)
        # live doctor (flags.doctor_live): end_pass above ran the rule
        # set over the committed records and emitted doctor.finding
        # events; surface the findings to the driver too — the operator
        # loop reads the end_pass dict, not the event stream
        findings = monitor.hub().last_doctor_findings
        if findings:
            out["doctor"] = findings
        if self._heartbeat is not None:
            self._heartbeat.publish()
        if self._col is not None:
            self._col.barrier("end_pass")
        return out

    def flip_phase(self) -> None:
        """Join↔update flip (box_wrapper.h:625); metrics follow the phase.

        (The reference's SetTestMode is covered by Trainer.eval_pass /
        PassWorkingSet(test_mode=True) — no separate box-level flag.)"""
        self.metrics.flip_phase()
        monitor.context.set_phase(self.phase)
        monitor.event("flip_phase", phase=self.phase)

    # ---- table hygiene ----

    def shrink_table(self, min_show: float, decay: float = 1.0) -> int:
        return self.store.shrink(min_show, decay)

    # ---- metric surface (box_helper_py.cc:87-110) ----

    def init_metric(self, name: str, **kw) -> None:
        self.metrics.init_metric(name, **kw)

    def get_metric_msg(self, name: str) -> dict[str, float]:
        return self.metrics.get_metric_msg(name)
