from paddlebox_tpu.fleet.boxps import BoxPS
from paddlebox_tpu.fleet.fleet_util import FleetUtil

__all__ = ["BoxPS", "FleetUtil"]
