"""Day/pass model persistence layout + donefiles — fleet_util semantics.

Reference (python/paddle/fluid/incubate/fleet/utils/fleet_util.py:649-745):
models are organized by day and pass under one output root —

    {root}/{day}/base/              full "batch model" (save_base):
                                    sparse/ snapshot + dense.npz
    {root}/{day}/delta-{pass}/      self-contained serving delta (save_delta):
                                    sparse delta-*.npz + dense.npz

with donefiles listing completed checkpoints so downstream (serving, resume)
can discover the newest model. A mid-day crash is recovered by loading the
newest base and replaying every delta donefile entry recorded after it —
the reference's pass-granularity restart model (SURVEY.md §5 "Failure
detection").

The output root may be REMOTE (``hdfs://…``/``afs://…`` — any scheme
registered with utils/fs.py; the reference saves day/pass models straight
to HDFS, fleet_util.py:674-745, over the AFS client of InitAfsAPI). Remote
saves stage locally then upload the checkpoint directory atomically-ish
(donefile written only after the upload), loads download to a temp dir;
local roots keep the direct-write path.
"""

from __future__ import annotations

import glob
import json
import os
import tempfile
import time
import warnings
from typing import Any

from paddlebox_tpu import monitor
from paddlebox_tpu.embedding import HostEmbeddingStore
from paddlebox_tpu.utils import checkpoint as ckpt_lib
from paddlebox_tpu.utils import faultpoint
from paddlebox_tpu.utils import fs as fs_lib


class FleetUtil:
    def __init__(self, output_root: str):
        self._fs, resolved = fs_lib.resolve(output_root)
        self._remote = fs_lib.is_remote(output_root)
        # file:// roots resolve to their plain local path; remote roots
        # keep the scheme (the commands want full URIs)
        self.root = output_root if self._remote else resolved
        self._fs.makedirs(self.root)
        # (donefile, lineno, line) already diagnosed — a tailer re-reads
        # the same file every poll, and one torn foreign line must not
        # re-warn/re-count forever (it would drown the alert signal)
        self._warned_malformed: set[tuple[str, int, str]] = set()

    # ---- paths ----

    def base_dir(self, day: int) -> str:
        return os.path.join(self.root, str(day), "base")

    def delta_dir(self, day: int, pass_id: int) -> str:
        return os.path.join(self.root, str(day), f"delta-{pass_id}")

    # ---- save (fleet_util.save_model:674 / save_delta_model:722) ----

    def save_model(self, store: HostEmbeddingStore, dense_state: Any,
                   day: int) -> str:
        """Full day-level base model: sparse base + dense snapshot."""
        path = self.base_dir(day)

        def write(into: str) -> None:
            store.save_base(os.path.join(into, "sparse"))
            ckpt_lib.save_pytree(dense_state, os.path.join(into, "dense.npz"))

        self._save_dir(path, write)
        self._write_donefile("base_model.donefile", day, 0, path)
        return path

    def save_delta_model(self, store: HostEmbeddingStore, dense_state: Any,
                         day: int, pass_id: int) -> str:
        """Pass-level delta (the reference's "xbox" online-serving delta).

        Self-contained: the directory named in the donefile holds BOTH the
        sparse delta plane and the dense snapshot, so a serving consumer can
        fetch exactly entry["path"].
        """
        path = self.delta_dir(day, pass_id)

        def write(into: str) -> None:
            sparse_dir = os.path.join(into, "sparse")
            os.makedirs(sparse_dir, exist_ok=True)
            store.save_delta(sparse_dir)
            ckpt_lib.save_pytree(dense_state, os.path.join(into, "dense.npz"))

        self._save_dir(path, write)
        self._write_donefile("delta_model.donefile", day, pass_id, path)
        return path

    def _save_dir(self, path: str, write) -> None:
        """Run `write(local_dir)` then land the directory at `path` —
        directly for local roots, stage-and-upload for remote ones (the
        donefile entry is only written after the upload completes, so a
        torn upload is never discoverable)."""
        if not self._remote:
            os.makedirs(path, exist_ok=True)
            write(path)
            return
        with tempfile.TemporaryDirectory(prefix="pbtpu_fleet_") as d:
            stage = os.path.join(d, "m")
            os.makedirs(stage)
            write(stage)
            faultpoint.hit("remote_ckpt.upload.pre")
            parent = path.rsplit("/", 1)[0]
            self._fs.makedirs(parent)
            # a leftover target (torn upload, re-save of the same day/pass)
            # must never nest the stage under it (fs_lib.put_replacing)
            fs_lib.put_replacing(self._fs, stage, path)

    def _write_donefile(self, name: str, day: int, pass_id: int,
                        path: str) -> None:
        self.append_donefile(name, {"day": day, "pass": pass_id,
                                    "path": path, "ts": int(time.time())},
                             dedup=("day", "pass", "path"))

    def append_donefile(self, name: str, entry: dict[str, Any],
                        dedup: tuple[str, ...] = ("path",)) -> bool:
        """Append one JSON line to a donefile under the output root.

        Crash-replay idempotent: the fs retry policy deliberately never
        retries append (utils/fs.py — a retried partial append could
        double-write), so a restarted save that reaches this line again
        must skip the append when the last committed line already carries
        the same values for the ``dedup`` keys. Returns False on skip.
        The serving publisher announces versions through this too —
        donefile discipline lives in ONE place.

        An interrupted compaction (``rewrite_donefile``) is repaired
        FIRST: a kill between the rewrite's rm and its put leaves only
        the ``.compact`` staging copy, and appending then would recreate
        the main file with one line, silently shadowing the whole
        history (the exact hazard the PR-6 snapshot-mirror compaction
        closed)."""
        self._repair_compaction(name)
        last = self.latest(name)
        if last is not None and all(last.get(k) == entry.get(k)
                                    for k in dedup):
            monitor.counter_add("fleet.donefile_dedup")
            return False
        self._fs.write_text(os.path.join(self.root, name),
                            json.dumps(entry) + "\n", append=True)
        return True

    def rewrite_donefile(self, name: str,
                         entries: list[dict[str, Any]]) -> None:
        """Two-phase compacting rewrite: the full compacted content
        lands in the ``.compact`` staging copy FIRST, then the main file
        is replaced and the staging copy removed. Readers
        (``_entries``) fall back to the staging copy in the rm→write
        window and ``append_donefile`` repairs an interrupted rewrite
        before extending — no kill point loses the donefile (the PR-6
        ``snapshots.donefile`` discipline, exposed here so the serving
        publisher's delta-chain compaction rides the ONE sanctioned
        donefile writer)."""
        path = os.path.join(self.root, name)
        alt = f"{path}.compact"
        content = "".join(json.dumps(e) + "\n" for e in entries)
        self._fs.write_text(alt, content)
        self._replace_main(path, content)
        self._fs.rm(alt)
        monitor.counter_add("fleet.donefile_compactions")
        monitor.event("donefile_compacted", donefile=name,
                      entries=len(entries))

    def _replace_main(self, path: str, content: str) -> None:
        """Land the rewritten main donefile. Local roots replace
        atomically (tmp → fsync → os.replace: NO torn-main window at
        all); remote roots keep the PR-6 rm→write sequence, whose only
        exposure is the window readers cover via the ``.compact``
        staging fallback."""
        if self._remote:
            if self._fs.exists(path):
                self._fs.rm(path)
            self._fs.write_text(path, content)
            return
        tmp = f"{path}.rewrite.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(content)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _repair_compaction(self, name: str) -> None:
        """Finish an interrupted rewrite_donefile: main file missing but
        the ``.compact`` staging copy present → restore main from it."""
        path = os.path.join(self.root, name)
        alt = f"{path}.compact"
        if self._fs.exists(path) or not self._fs.exists(alt):
            return
        content = "".join(ln if ln.endswith("\n") else ln + "\n"
                          for ln in self._fs.read_lines(alt))
        self._replace_main(path, content)
        self._fs.rm(alt)
        monitor.counter_add("fleet.donefile_repairs")
        monitor.event("donefile_repaired", donefile=name)

    def entries(self, donefile: str) -> list[dict[str, Any]]:
        """All parseable entries of a donefile, in append order (public
        form of the discovery walk — compaction policies read this)."""
        return self._entries(donefile)

    def _entries(self, donefile: str) -> list[dict[str, Any]]:
        fname = os.path.join(self.root, donefile)
        if not self._fs.exists(fname):
            # mid-compaction window: the staging copy is the donefile
            alt = f"{fname}.compact"
            if self._fs.exists(alt):
                fname = alt
            else:
                return []
        out = []
        for lineno, line in enumerate(self._fs.read_lines(fname), 1):
            line = line.strip()
            if not line:
                continue
            # a half-written/foreign line must not brick model discovery:
            # writers append atomically-at-best (a crashed foreign writer,
            # or a non-JSON marker a tool dropped in, leaves a torn line)
            # — skip it WITH A NAME, never raise mid-parse. Consumers fall
            # back to the surviving entries; the publisher's re-announce
            # after resume re-lands anything the torn line was meant to
            # carry.
            try:
                e = json.loads(line)
                if not isinstance(e, dict):
                    raise ValueError(f"entry is {type(e).__name__}, "
                                     f"not an object")
            except ValueError as err:
                seen = (donefile, lineno, line)
                if seen not in self._warned_malformed:
                    self._warned_malformed.add(seen)
                    monitor.counter_add("fleet.donefile_malformed_lines")
                    monitor.event("donefile_malformed_line",
                                  donefile=donefile, lineno=lineno,
                                  error=str(err)[:200])
                    warnings.warn(
                        f"malformed line {lineno} in donefile {donefile!r} "
                        f"(skipped): {line[:120]!r} ({err})")
                continue
            out.append(e)
        return out

    def latest(self, donefile: str = "base_model.donefile"
               ) -> dict[str, Any] | None:
        entries = self._entries(donefile)
        return entries[-1] if entries else None

    # ---- load (fleet_util.load_model:649) ----

    def load_model(self, dense_template: Any, day: int | None = None
                   ) -> tuple[HostEmbeddingStore, Any, int]:
        """Load the newest base model (or the given day's) and replay every
        delta checkpointed after it, in donefile order.

        Returns (store, dense_state, day). `dense_template` supplies the
        pytree structure for the dense plane.
        """
        bases = self._entries("base_model.donefile")
        if day is not None:
            bases = [b for b in bases if int(b["day"]) == day]
        if not bases:
            raise FileNotFoundError(
                f"no base model{f' for day {day}' if day else ''} in {self.root}")
        with tempfile.TemporaryDirectory(prefix="pbtpu_fetch_") as tmp:
            # newest base first; a base whose download fails (remote-FS
            # outage surviving the CommandFS retry budget) is diagnosed
            # and skipped — recovery falls back to the previous committed
            # base + its delta replay rather than dying on the freshest
            base, base_local, fetch_err = None, None, None
            for i, cand in enumerate(reversed(bases)):
                try:
                    base_local = self._fetch_dir(cand["path"], tmp,
                                                 f"base{i}")
                    base = cand
                    break
                except RuntimeError as e:
                    fetch_err = e
                    monitor.counter_add("fleet.base_fetch_fallbacks")
                    monitor.event("fleet_base_fetch_fallback",
                                  path=cand["path"], error=str(e)[:300])
                    warnings.warn(
                        f"base model {cand['path']} failed to download "
                        f"({e}); falling back to the previous donefile "
                        f"entry")
            if base is None:
                raise RuntimeError(
                    f"every base model donefile entry failed to download "
                    f"from {self.root} (last: {fetch_err})")
            day = int(base["day"])
            store = HostEmbeddingStore.load(os.path.join(base_local,
                                                         "sparse"))
            dense_file = os.path.join(base_local, "dense.npz")
            # replay deltas recorded after this base (mid-day-crash
            # recovery: yesterday's base + today's pass deltas)
            for i, d in enumerate(self._entries("delta_model.donefile")):
                if int(d["ts"]) < int(base["ts"]) or d["path"] == base["path"]:
                    continue
                if int(d["day"]) < day:
                    continue
                try:
                    d_local = self._fetch_dir(d["path"], tmp, f"d{i}")
                except RuntimeError as e:
                    # a delta is state, not discovery: skipping one would
                    # silently serve a model missing a pass — fail with
                    # the donefile identity in the diagnosis
                    raise RuntimeError(
                        f"delta model {d['path']} (day {d['day']} pass "
                        f"{d['pass']}) failed to download during recovery "
                        f"replay: {e}") from e
                for f in sorted(glob.glob(os.path.join(d_local, "sparse",
                                                       "delta-*.npz"))):
                    store.apply_delta_file(f)
                cand = os.path.join(d_local, "dense.npz")
                if os.path.exists(cand):
                    dense_file = cand
                day = max(day, int(d["day"]))
            dense = ckpt_lib.load_pytree(dense_template, dense_file)
        return store, dense, day

    def _fetch_dir(self, path: str, tmp: str, tag: str) -> str:
        """Local view of a checkpoint dir: itself locally, a download when
        the root is remote."""
        if not self._remote:
            return path
        faultpoint.hit("remote_ckpt.download.pre")
        local = os.path.join(tmp, tag)
        self._fs.get(path, local)
        return local
