"""Tracing, counters, nan guards, and debug dump streams.

The reference's observability stack (SURVEY.md §5):

- ``RecordEvent`` RAII spans + chrome-trace timelines — platform/profiler.{h,cc}
  (RecordEvent, profiler.cc:303) and device_tracer.cc:815 (CUPTI → chrome
  trace). Here: :class:`RecordEvent` spans collected by a process-global
  profiler, exported with :func:`export_chrome_trace`; device-side traces
  delegate to ``jax.profiler`` (:func:`start_device_trace`), whose TensorBoard
  dumps play the CUPTI role on TPU. Spans are tagged with the current
  pass/step (``monitor.context``) and the buffer is a bounded ring
  (``flags.profiler_max_events``) with a dropped-span counter — a day-scale
  run can leave the profiler on without growing without limit.
- global stat counters — platform/monitor.h ``StatRegistry``/``STAT_ADD``
  (monitor.h:76,129). The registry now lives in
  :mod:`paddlebox_tpu.monitor.registry` (the telemetry hub owns it);
  ``StatRegistry``/``STATS``/``stat_add`` here are back-compat shims over
  the same object — new code should use ``monitor.counter_add``.
- nan/inf safety net — ``FLAGS_check_nan_inf`` + details/nan_inf_utils
  (CheckBatchNanOrInfRet dumps the whole scope on trip,
  boxps_worker.cc:575-580). Here: :func:`find_nonfinite` walks a pytree and
  :func:`dump_tree` snapshots it to an .npz next to the raised error
  (wired into the trainer via ``flags.check_nan_inf``).
- per-batch field/param dump threads — DumpField/DumpParam
  (device_worker.cc; dump channel + threads boxps_trainer.cc:96-108, proto
  knobs trainer_desc.proto:39-45). Here: :class:`DumpStream`, a
  background-thread line writer the trainer feeds per batch; the writer
  thread inherits the trainer's pass/step context so its telemetry is
  tagged.
"""

from __future__ import annotations

import collections
import json
import os
import queue
import threading
import time
from typing import Any, Iterable

import numpy as np

from paddlebox_tpu.config import flags as _flags
from paddlebox_tpu.monitor import context as _mon_ctx
from paddlebox_tpu.monitor.registry import STATS, StatRegistry  # noqa: F401

# ---------------------------------------------------------------------------
# RecordEvent spans + chrome trace
# ---------------------------------------------------------------------------

_events: collections.deque = collections.deque()
_events_lock = threading.Lock()
_enabled = False
_dropped = 0
_t0 = time.perf_counter()


def enable_profiler() -> None:
    """Start collecting RecordEvent spans (profiler.cc EnableProfiler)."""
    global _enabled, _t0, _dropped
    with _events_lock:
        _events.clear()
        _dropped = 0
        _t0 = time.perf_counter()
    _enabled = True


def disable_profiler() -> None:
    global _enabled
    _enabled = False


def profiler_events() -> list[dict]:
    with _events_lock:
        return list(_events)


def dropped_spans() -> int:
    """Spans evicted from the ring since enable_profiler() (satellite of
    the bounded buffer: a day-scale run drops oldest-first past
    ``flags.profiler_max_events`` instead of growing unbounded)."""
    return _dropped


def _append_event(ev: dict) -> None:
    global _dropped
    cap = _flags.profiler_max_events
    with _events_lock:
        if cap and len(_events) >= cap:
            _events.popleft()
            _dropped += 1
            STATS.add("profiler.dropped_spans", 1)
        _events.append(ev)


def _ctx_args(extra: dict | None = None) -> dict | None:
    """pass/step tags for a chrome event (None outside a pass, no args key)."""
    c = _mon_ctx.current()
    if c.pass_id is None and not extra:
        return None
    args = {} if c.pass_id is None else {"pass_id": c.pass_id,
                                         "step": c.step}
    if extra:
        args.update(extra)
    return args


def record_span(name: str, start: float, end: float,
                args: dict | None = None) -> None:
    """Record one complete span (perf_counter endpoints). The
    ``start >= _t0`` guard drops spans that straddle an enable_profiler()
    reset — they belong to neither trace."""
    if not _enabled or start < _t0:
        return
    ev = {
        "name": name,
        "ph": "X",
        "ts": (start - _t0) * 1e6,        # chrome trace is in µs
        "dur": (end - start) * 1e6,
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0xFFFFFFFF,
    }
    a = _ctx_args(args)
    if a:
        ev["args"] = a
    _append_event(ev)


def record_instant(name: str, args: dict | None = None) -> None:
    """Record a chrome-trace instant marker (``ph: i``) — pass boundaries
    and checkpoint commits use these so a Perfetto timeline reads in pass
    units."""
    if not _enabled:
        return
    ev = {
        "name": name,
        "ph": "i",
        "s": "g",                          # global-scope instant line
        "ts": (time.perf_counter() - _t0) * 1e6,
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0xFFFFFFFF,
    }
    a = _ctx_args(args)
    if a:
        ev["args"] = a
    _append_event(ev)


class RecordEvent:
    """Named span: context manager or decorator.

    ``with RecordEvent("translate"): ...`` records a complete-event when the
    profiler is enabled; negligible cost when disabled. (For spans that
    should ALSO reach the telemetry event stream, use ``monitor.span`` —
    it forwards here when the profiler is on.)
    """

    def __init__(self, name: str):
        self.name = name
        self._start: float | None = None

    def __enter__(self):
        # latch enabled-ness here: if the profiler flips on mid-span the
        # half-open span is skipped rather than emitted with a garbage start
        self._start = time.perf_counter() if _enabled else None
        return self

    def __exit__(self, *exc):
        if _enabled and self._start is not None:
            record_span(self.name, self._start, time.perf_counter())
        return False

    def __call__(self, fn):
        def wrapped(*a, **kw):
            with RecordEvent(self.name):
                return fn(*a, **kw)
        wrapped.__name__ = getattr(fn, "__name__", self.name)
        return wrapped


def export_chrome_trace(path: str) -> int:
    """Write collected spans as a chrome://tracing / Perfetto JSON file.

    Returns the number of events written (the profiler.proto → chrome-trace
    path of device_tracer.cc:815, host spans only). Includes the
    pass-boundary / checkpoint-commit instant markers recorded via
    :func:`record_instant`."""
    evs = profiler_events()
    # atomic tmp->fsync->replace: a crash mid-export must not leave a torn
    # trace under the final name (Perfetto half-loads truncated JSON, and
    # a monitoring cron shipping the file would ship the torn copy)
    from paddlebox_tpu.utils.checkpoint import atomic_file
    with atomic_file(path) as tmp:
        with open(tmp, "w") as f:
            json.dump({"traceEvents": evs, "displayTimeUnit": "ms"}, f)
    return len(evs)


def start_device_trace(logdir: str) -> None:
    """Begin a device-level trace via jax.profiler (CUPTI's role on TPU —
    the dump is read with TensorBoard or xprof)."""
    import jax
    jax.profiler.start_trace(logdir)


def stop_device_trace() -> None:
    import jax
    jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# StatRegistry (platform/monitor.h) — back-compat shims over
# monitor.registry.STATS; new call sites use monitor.counter_add/gauge_set.
# ---------------------------------------------------------------------------

def stat_add(name: str, value: float = 1.0) -> None:  # STAT_ADD(name, v)
    STATS.add(name, value)


def stat_get(name: str) -> float:
    return STATS.get(name)


def stat_set(name: str, value: float) -> None:
    STATS.set(name, value)


# ---------------------------------------------------------------------------
# nan/inf guard (details/nan_inf_utils)
# ---------------------------------------------------------------------------

def host_local(a: Any) -> np.ndarray:
    """np.asarray that survives multi-host sharded jax arrays: falls back to
    concatenating this host's addressable shards along axis 0 (right for
    batch-dim sharding; each host dumps its own slice)."""
    try:
        return np.asarray(a)
    except RuntimeError:
        shards = getattr(a, "addressable_shards", None)
        if not shards:
            raise
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)


def find_nonfinite(tree: Any) -> list[str]:
    """Paths of pytree leaves containing nan/inf (empty list = all finite)."""
    import jax
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = host_local(leaf)
        if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(arr).all():
            bad.append(jax.tree_util.keystr(path))
    return bad


def dump_tree(path: str, tree: Any) -> str:
    """Snapshot a pytree to ``<path>.npz`` (the dump-all-scope behavior of
    CheckBatchNanOrInfRet's trip handler). Returns the file written."""
    import jax
    flat = {}
    for p, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[jax.tree_util.keystr(p)] = host_local(leaf)
    out = path if path.endswith(".npz") else path + ".npz"
    os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
    np.savez(out, **flat)
    return out


# ---------------------------------------------------------------------------
# DumpStream (DumpField/DumpParam channel + threads)
# ---------------------------------------------------------------------------

def _col_formatter(v):
    """Per-instance formatter for one dump column, run on the writer thread.

    Accepts a 1-D array (scalar per instance), a 2-D array (multi-value
    float slot — comma-joined), or an ``(ids, mask)`` pair (sparse slot —
    the masked ids comma-joined). Keeping the per-instance string work here
    is the point of the deferred job: the training thread never formats.
    """
    if isinstance(v, tuple):
        ids, mask = v
        return lambda i: ",".join(
            str(x) for x, ok in zip(ids[i], mask[i]) if ok)
    if getattr(v, "ndim", 1) >= 2:
        return lambda i: ",".join(f"{x:g}" for x in v[i])
    return lambda i: f"{v[i]}"


class DumpStream:
    """Background-thread line dumper.

    The trainer enqueues formatted lines per batch; a writer thread drains
    the queue to ``path`` — same shape as the reference's dump channel +
    dump_thread_num threads writing debug fields to (HDFS-bound) files
    (boxps_trainer.cc:96-108). Local filesystem here; pluggable later.
    The writer thread inherits the spawner's pass/step context
    (``monitor.context.spawn``) so its line counters and telemetry events
    are attributed to the pass being dumped.
    """

    def __init__(self, path: str, mode: str = "w"):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._q: queue.Queue[str | tuple | None] = queue.Queue(maxsize=4096)
        self._error: BaseException | None = None
        self._f = open(path, mode)
        self._thread = _mon_ctx.spawn(self._drain, name="pbtpu-dump-writer")
        self._thread.start()

    def _drain(self):
        from paddlebox_tpu.monitor.hub import _HUB
        while True:
            job = self._q.get()
            if job is None:
                break
            if self._error is not None:  # after a write error: keep
                continue                 # consuming so producers never block
            try:
                if isinstance(job, str):
                    self._f.write(job)
                    STATS.add("dump_stream.lines", 1)
                else:  # deferred field-formatting job (see write_fields)
                    step, preds, labels, cols = job
                    fmts = {k: _col_formatter(v) for k, v in cols.items()}
                    out = []
                    for i in range(len(preds)):
                        tail = "".join(f" {k}:{fmt(i)}"
                                       for k, fmt in fmts.items())
                        out.append(f"{step} {i} {preds[i]:.6f} "
                                   f"{labels[i]:g}{tail}\n")
                    self._f.write("".join(out))
                    STATS.add("dump_stream.lines", len(out))
                    if _HUB._enabled:    # tagged from THIS writer thread
                        _HUB.event("dump_fields_written", lines=len(out),
                                   dump_step=int(step))
            except BaseException as e:
                self._error = e

    def write(self, line: str) -> None:
        if not line.endswith("\n"):
            line += "\n"
        self._q.put(line)

    def write_fields(self, step: int, preds: Iterable[float],
                     labels: Iterable[float],
                     extra: dict[str, Iterable[Any]] | None = None) -> None:
        """Per-instance dump: ``step <i> pred label [k:v ...]`` lines —
        DumpField's instance-major text format. Only the (cheap) host
        conversion happens here; the per-instance string formatting runs on
        the writer thread so the training loop isn't serialized behind it."""
        preds = host_local(preds).reshape(-1)
        labels = host_local(labels).reshape(-1)

        def col(v):
            if isinstance(v, tuple):      # (ids, mask) sparse slot pair
                return tuple(host_local(x) for x in v)
            v = host_local(v)
            return v if getattr(v, "ndim", 1) >= 2 else v.reshape(-1)

        cols = {k: col(v) for k, v in (extra or {}).items()}
        self._q.put((int(step), preds, labels, cols))

    def close(self) -> None:
        self._q.put(None)
        self._thread.join()
        self._f.close()
        if self._error is not None:  # surface a mid-stream write failure
            raise RuntimeError(
                f"DumpStream writer failed for {self.path}") from self._error

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
