"""Pluggable filesystems — the AFS/HDFS I/O role.

Reference: the Box stack reads filelists and writes day/pass checkpoints on
AFS/HDFS — ``BoxWrapper::InitAfsAPI(fs_name, fs_user, pass, conf)``
(box_wrapper.h:577) hands cluster credentials to libbox_ps's PaddleFileMgr,
``HdfsStore`` (gloo_wrapper.h:106,45) does rendezvous on HDFS, and the
fleet_util day/pass model save targets HDFS paths (fleet_util.py:674-745).
The open-source glue shells out to ``hadoop fs`` clients for the same job.

TPU-native rendering: one small interface with two implementations —

- :class:`LocalFS`: plain POSIX (the default for schemeless paths).
- :class:`CommandFS`: every operation is a configurable shell command
  (``{path}``/``{src}``/``{dst}`` templates). This is deliberately the
  general escape hatch of this environment: the same class speaks
  ``hadoop fs``, ``gsutil``, ``aws s3``, or an in-house CLI, and a test can
  back it with plain ``cat``/``cp``. The reference's closed AFS client
  collapses into command templates the operator controls.

Paths carry their filesystem by URI scheme (``hdfs://…``, ``afs://…``);
:func:`resolve` splits a path into (filesystem, fs-native path).
``init_afs_api`` mirrors the reference's call shape and registers a
hadoop-style CommandFS for the ``afs``/``hdfs`` schemes.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import tempfile
from typing import IO, Iterator


class FileSystem:
    """Interface. Paths are fs-native (scheme included is fine — commands
    usually want the full URI; LocalFS strips nothing because local paths
    never carry a scheme)."""

    def open_read(self, path: str) -> IO[bytes]:
        raise NotImplementedError

    def read_lines(self, path: str) -> Iterator[str]:
        with self.open_read(path) as f:
            for raw in f:
                yield raw.decode("utf-8", errors="replace")

    def write_text(self, path: str, text: str, append: bool = False) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def ls(self, path: str) -> list[str]:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def put(self, local: str, remote: str) -> None:
        """Upload a local file or directory tree."""
        raise NotImplementedError

    def get(self, remote: str, local: str) -> None:
        """Download a remote file or directory tree."""
        raise NotImplementedError

    def rm(self, path: str) -> None:
        raise NotImplementedError


class LocalFS(FileSystem):
    def open_read(self, path: str) -> IO[bytes]:
        return open(path, "rb")

    def write_text(self, path: str, text: str, append: bool = False) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "a" if append else "w") as f:
            f.write(text)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def ls(self, path: str) -> list[str]:
        return sorted(os.path.join(path, n) for n in os.listdir(path))

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def put(self, local: str, remote: str) -> None:
        if local != remote:
            import shutil
            if os.path.isdir(local):
                shutil.copytree(local, remote, dirs_exist_ok=True)
            else:
                os.makedirs(os.path.dirname(os.path.abspath(remote)),
                            exist_ok=True)
                shutil.copy2(local, remote)

    def get(self, remote: str, local: str) -> None:
        self.put(remote, local)

    def rm(self, path: str) -> None:
        import shutil
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)


class CommandFS(FileSystem):
    """Shell-command-backed filesystem (hadoop fs / gsutil / aws s3 …).

    Templates substitute ``{path}``, ``{src}``, ``{dst}``. Defaults speak
    the ``hadoop fs`` dialect; pass your own for other CLIs. ``env`` merges
    into the subprocess environment (credentials — the fs_user/fs_passwd of
    InitAfsAPI travel here, never through the conversation of a command
    line that ``ps`` could show, when the CLI supports env auth).

    Resilience: ``put``/``get``/``ls``/``rm`` (and idempotent ``mkdir -p``)
    retry with exponential backoff (``retries`` total attempts, first
    sleep ``retry_backoff`` seconds, doubling), and every non-streaming
    command is bounded by ``timeout`` seconds (None or 0 = unbounded; a
    hung client counts as a failed attempt). A failed ``get`` attempt's
    partial local dst is removed before the retry (the default hadoop
    ``-get`` refuses to overwrite, so a leftover half-download would turn
    every retry into 'File exists'). Exhaustion raises with the attempt
    count and the last stderr. ``append`` is deliberately NOT retried — a
    partial append that reported failure could double-write a donefile
    line — and ``test``'s exists/absent exit codes are both successes, so
    it never retries a legitimate "absent". Defaults come from
    flags.fs_retry_attempts / fs_retry_backoff_s / fs_command_timeout_s
    at call time.
    """

    _RETRY_OPS = ("put", "get", "ls", "rm", "mkdir")

    def __init__(self, cat: str = "hadoop fs -cat {path}",
                 ls: str = "hadoop fs -ls {path}",
                 put: str = "hadoop fs -put -f {src} {dst}",
                 get: str = "hadoop fs -get {src} {dst}",
                 mkdir: str = "hadoop fs -mkdir -p {path}",
                 test: str = "hadoop fs -test -e {path}",
                 rm: str = "hadoop fs -rm -r -f {path}",
                 append: str | None = None,
                 env: dict | None = None,
                 retries: int | None = None,
                 retry_backoff: float | None = None,
                 timeout: float | None = None):
        self._cmds = {"cat": cat, "ls": ls, "put": put, "get": get,
                      "mkdir": mkdir, "test": test, "rm": rm,
                      "append": append}
        self._env = dict(os.environ, **(env or {}))
        self._retries = retries
        self._retry_backoff = retry_backoff
        self._timeout = timeout

    def _retry_policy(self, op: str) -> tuple[int, float, float | None]:
        """(attempts, first_backoff_seconds, timeout_seconds_or_None)."""
        from paddlebox_tpu.config import flags
        attempts = (self._retries if self._retries is not None
                    else flags.fs_retry_attempts)
        if op not in self._RETRY_OPS:
            attempts = 1
        backoff = (self._retry_backoff if self._retry_backoff is not None
                   else flags.fs_retry_backoff_s)
        # 0 means "no timeout" in both the ctor and the flag
        timeout = (self._timeout if self._timeout is not None
                   else flags.fs_command_timeout_s) or None
        return max(1, int(attempts)), float(backoff), timeout

    def _argv(self, op: str, **kw) -> list[str]:
        tpl = self._cmds[op]
        if tpl is None:
            raise NotImplementedError(f"CommandFS has no {op!r} command")
        # substitute only the known placeholders (not str.format): literal
        # '{'/'}' are legal in object names and in command templates.
        # Single-pass re.sub so a substituted VALUE containing "{dst}" etc.
        # is never re-scanned by a later placeholder.
        if not kw:
            # "|".join([]) would compile to an everywhere-matching empty
            # pattern whose replacement callback KeyErrors on kw[""]
            return shlex.split(tpl)
        import re
        pat = re.compile("|".join(re.escape("{" + k + "}") for k in kw))
        return [pat.sub(lambda m: kw[m.group(0)[1:-1]], tok)
                for tok in shlex.split(tpl)]

    def _run(self, op: str, ok_codes: tuple = (0,),
             **kw) -> subprocess.CompletedProcess:
        import time

        from paddlebox_tpu import monitor
        attempts, backoff, timeout = self._retry_policy(op)
        argv = self._argv(op, **kw)
        # get-retry hygiene targets: only paths a failed attempt may have
        # CREATED are ever cleaned up between attempts — a dst (or member
        # inside a pre-existing dst directory) that existed before the
        # first attempt is never touched
        get_cleanup: list[str] = []
        if op == "get" and attempts > 1 and "dst" in kw:
            dst = kw["dst"]
            if not os.path.exists(dst):
                get_cleanup.append(dst)
            elif os.path.isdir(dst) and "src" in kw:
                member = os.path.join(
                    dst, os.path.basename(kw["src"].rstrip("/")))
                if not os.path.exists(member):
                    get_cleanup.append(member)
        last = "never ran"
        for attempt in range(1, attempts + 1):
            monitor.counter_add(f"fs.{op}.attempts")
            try:
                proc = subprocess.run(argv, env=self._env,
                                      capture_output=True, timeout=timeout)
            except subprocess.TimeoutExpired:
                last = f"timed out after {timeout}s"
                monitor.counter_add(f"fs.{op}.timeouts")
            else:
                if proc.returncode in ok_codes:
                    if attempt > 1:
                        # a retry that eventually succeeded — the
                        # flaky-storage signature the flight record keys on
                        monitor.counter_add(f"fs.{op}.recovered")
                    return proc
                last = (f"exit {proc.returncode}: "
                        f"{proc.stderr.decode(errors='replace')[:500]}")
            if attempt < attempts:
                monitor.counter_add(f"fs.{op}.retries")
                for p in get_cleanup:
                    # a dead/timed-out client may have left a partial
                    # local download; `-get` without -f would then fail
                    # every retry with 'File exists'
                    try:
                        if os.path.isdir(p):
                            import shutil
                            shutil.rmtree(p)
                        elif os.path.exists(p):
                            os.remove(p)
                    # pblint: disable=silent-except -- between-attempt
                    # hygiene: if the partial dst survives, the retried
                    # -get fails loudly with 'File exists' anyway
                    except OSError:
                        pass
                time.sleep(backoff * (2 ** (attempt - 1)))
        monitor.counter_add(f"fs.{op}.exhausted")
        monitor.event("fs_exhausted", op=op, attempts=attempts,
                      error=last[:300])
        raise RuntimeError(
            f"CommandFS {op} failed after {attempts} attempt"
            f"{'s' if attempts != 1 else ''} ({last})")

    def open_read(self, path: str) -> IO[bytes]:
        # stderr spools to a temp file: a chatty CLI (hadoop log4j noise)
        # writing >64KB to a PIPE nobody drains would deadlock the stream
        errf = tempfile.TemporaryFile()
        proc = subprocess.Popen(self._argv("cat", path=path),
                                env=self._env, stdout=subprocess.PIPE,
                                stderr=errf)
        assert proc.stdout is not None
        return _CommandStream(proc, errf)

    def write_text(self, path: str, text: str, append: bool = False) -> None:
        if append and self._cmds["append"] is None and self.exists(path):
            # no append command: read-modify-write (donefile sizes are tiny)
            with self.open_read(path) as f:
                text = f.read().decode() + text
            append = False
        with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                         delete=False) as tmp:
            tmp.write(text)
            name = tmp.name
        try:
            if append and self._cmds["append"] is not None:
                self._run("append", src=name, dst=path)
            else:
                self._run("put", src=name, dst=path)
        finally:
            os.unlink(name)

    def exists(self, path: str) -> bool:
        """Exit 0 = exists, exit 1 = does not exist; anything else (network
        outage, auth failure) RAISES — conflating an outage with "absent"
        would let write_text's append fallback truncate a donefile."""
        return self._run("test", ok_codes=(0, 1),
                         path=path).returncode == 0

    def ls(self, path: str) -> list[str]:
        out = self._run("ls", path=path).stdout.decode(errors="replace")
        names = []
        for line in out.splitlines():
            if not line.strip() or line.startswith("Found "):
                continue
            # hadoop-style -ls lines carry 8 whitespace fields with the
            # path LAST (it may contain spaces — split at most 7 times so
            # the path field keeps them); bare-name listings (plain `ls`)
            # are a single field. Custom ls templates must emit one of
            # those two shapes.
            names.append(line.split(None, 7)[-1])
        return sorted(names)

    def makedirs(self, path: str) -> None:
        self._run("mkdir", path=path)

    def put(self, local: str, remote: str) -> None:
        self._run("put", src=local, dst=remote)

    def get(self, remote: str, local: str) -> None:
        self._run("get", src=remote, dst=local)

    def rm(self, path: str) -> None:
        self._run("rm", path=path)


class _CommandStream:
    """File-like over a streaming subprocess stdout; close() reaps the
    process and raises if the command failed (a silently-truncated filelist
    must never parse as a short success)."""

    def __init__(self, proc: subprocess.Popen, errf=None):
        self._proc = proc
        self._f = proc.stdout
        self._errf = errf

    def read(self, *a):
        return self._f.read(*a)

    def __iter__(self):
        return iter(self._f)

    def close(self) -> None:
        if self._f.closed:
            return
        # An early-exit consumer (head of a multi-GB remote file) must not
        # pay a full download inside close(): if any bytes remain, kill the
        # producer and skip the exit-code check — the strict rc!=0 check
        # (truncated filelists must never parse as short successes) is
        # reserved for fully-consumed streams, where it is meaningful.
        if self._f.read(1):
            self._proc.kill()
            self._proc.wait()
            self._f.close()
            if self._errf is not None:
                self._errf.close()
            return
        rc = self._proc.wait()
        err = ""
        if self._errf is not None:
            self._errf.seek(0)
            err = self._errf.read(4096).decode(errors="replace")
            self._errf.close()
            self._errf = None
        self._f.close()   # before the raise: no fd leak, close idempotent
        if rc != 0:
            raise RuntimeError(f"CommandFS cat failed ({rc}): {err[:500]}")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# scheme registry
# ---------------------------------------------------------------------------

_REGISTRY: dict[str, FileSystem] = {}
_LOCAL = LocalFS()


def register_fs(scheme: str, fs: FileSystem) -> None:
    _REGISTRY[scheme.rstrip(":/").lower()] = fs


def resolve(path: str) -> tuple[FileSystem, str]:
    """Path → (filesystem, path). Schemeless (or file://) paths are local;
    an unregistered scheme is an error, not a silent local fallback."""
    if "://" in path:
        scheme = path.split("://", 1)[0].lower()
        if scheme == "file":
            return _LOCAL, path.split("://", 1)[1]
        fs = _REGISTRY.get(scheme)
        if fs is None:
            raise ValueError(
                f"no filesystem registered for scheme {scheme!r} "
                f"(register_fs / init_afs_api first)")
        return fs, path
    return _LOCAL, path


def is_remote(path: str) -> bool:
    return "://" in path and not path.lower().startswith("file://")


def put_replacing(fs: FileSystem, local: str, remote: str) -> None:
    """Upload a directory (or file) REPLACING any leftover target first.

    `hadoop fs -put` into an EXISTING directory nests the source under it
    (``remote/basename(local)``) while every donefile/manifest consumer
    expects the content AT ``remote`` — so a torn previous upload or a
    re-save of the same version would silently double-nest. Every
    dir-upload site (checkpoint mirror, fleet day/pass models, serving
    publish) must go through this rm-then-put."""
    fs.rm(remote)
    fs.put(local, remote)


def init_afs_api(fs_name: str, fs_user: str = "", fs_passwd: str = "",
                 conf_path: str = "", hadoop_bin: str = "hadoop",
                 schemes: tuple = ("afs", "hdfs")) -> CommandFS:
    """Reference call shape (InitAfsAPI, box_wrapper.h:577; pybind
    box_helper_py.cc:105): configure the cluster client once, then every
    remote path in filelists/checkpoint roots just works.

    fs_name is the defaultFS (e.g. ``hdfs://ns1``); credentials ride
    ``-D`` confs like the reference's ugi string.
    """
    d = []
    env = {}
    if fs_name:
        d.append(f"-Dfs.defaultFS={fs_name}")
    if fs_user:
        # credentials ride HADOOP_CLIENT_OPTS (the client-JVM env hook),
        # not the wrapper argv — `ps` on the launcher shows no secret
        env["HADOOP_CLIENT_OPTS"] = (
            f"-Dhadoop.job.ugi={fs_user},{fs_passwd}")
    opts = " ".join(d)
    # --config is a launcher option: it must precede the `fs` subcommand
    conf = f" --config {conf_path}" if conf_path else ""
    base = f"{hadoop_bin}{conf} fs {opts}".strip()
    fs = CommandFS(cat=f"{base} -cat {{path}}",
                   ls=f"{base} -ls {{path}}",
                   put=f"{base} -put -f {{src}} {{dst}}",
                   get=f"{base} -get {{src}} {{dst}}",
                   mkdir=f"{base} -mkdir -p {{path}}",
                   test=f"{base} -test -e {{path}}",
                   rm=f"{base} -rm -r -f {{path}}",
                   env=env)
    for s in schemes:
        register_fs(s, fs)
    return fs
