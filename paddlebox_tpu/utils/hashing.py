"""Stable 64-bit hashing.

The reference routes global-shuffle records by ``XXH64(ins_id)`` and
``search_id % mpi_size`` (reference data_set.cc:1934-1942) and signs features
into a uint64 key space. We need a stable, fast 64-bit hash that is identical
across hosts and across Python/C++ — FNV-1a 64 fits (xxhash isn't in the
baked-in dependency set, and hash() is salted per process).
"""

from __future__ import annotations

import numpy as np

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = 0xFFFFFFFFFFFFFFFF


def hash64(s: str | bytes) -> int:
    if isinstance(s, str):
        s = s.encode("utf-8")
    h = _FNV_OFFSET
    for b in s:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK
    return h


def hash64_array(a: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over a uint64/int64 array — used to hash raw
    feature signs into table shards deterministically."""
    x = a.astype(np.uint64, copy=True)
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(_MASK)
        z = x
        z = ((z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(_MASK)
        z = ((z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) & np.uint64(_MASK)
        z = z ^ (z >> np.uint64(31))
    return z
