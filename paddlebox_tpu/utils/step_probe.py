"""Per-stage device-time attribution of the train step.

The reference prints a read/trans/cal/sync split per pass
(``log_for_profile``, boxps_worker.cc:746-759); this module is the
device-side analogue for the jitted TPU step: it measures each stage of
the step — embedding ``lookup``, ``dense_fwd_bwd``, ``sparse_push``
(which includes the payload reorder, pack, and binned kernel), and the
``dispatch_floor`` (per-program launch cost, measured with a no-op step
of identical signature) — as wall-free DEVICE time; the remainder is
``unattributed_seconds`` (fusion/overlap differences between isolated
stages and the real fused step). The bench embeds the result
(``attribute_step``) so a throughput regression names its stage.

Measurement discipline (see bench.py module docstring): a single jit call
over the tunnel costs ~4-6ms of dispatch, and ``block_until_ready``
returns early — so every stage is measured by repeating it K times INSIDE
one jit, chained through ``lax.optimization_barrier`` so XLA can neither
hoist the loop-invariant body nor dead-code it, and every window is
terminated by a real 4-byte D2H. Per-call time is (window - empty_window)
/ K, where the empty window (same K-iteration fori_loop over a barrier
no-op) measures the dispatch + loop floor.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _sync(x) -> float:
    return float(np.asarray(jax.tree.leaves(x)[0].reshape(-1)[0]))


def _all_alive(*trees) -> bool:
    """True iff no leaf has been invalidated by donation. A step that
    fails DURING execution has already consumed its donated inputs; the
    recovery rebind must not touch those (reading them raises and would
    mask the original, real error)."""
    for leaf in jax.tree.leaves(trees):
        if getattr(leaf, "is_deleted", lambda: False)():
            return False
    return True


def timed_repeat(fn: Callable, args: tuple, k: int = 32,
                 warmup: int = 2) -> float:
    """Device seconds per fn(*args) call, dispatch-subtracted.

    fn must return an array (or pytree). Iterations are data-chained so
    the body stays inside the loop and none of it dead-codes: EVERY leaf
    of the output is reduced with jnp.sum, the sums feed the next
    iteration's carry through an optimization_barrier, and the carry
    perturbs fn's first argument. The sum is a full read of the output —
    a small, bandwidth-bounded overhead included in the reported time
    (it cancels when comparing variants with equal output shapes).
    """

    def chained(carry_arg, *rest):
        def body(_, state):
            c, acc = state
            out = fn(c, *rest)
            # full data dependence on out: nothing in fn can be DCE'd
            s = jnp.asarray(0.0, jnp.float32)
            for leaf in jax.tree.leaves(out):
                s = s + jnp.sum(leaf).astype(jnp.float32)
            c2, s2 = lax.optimization_barrier((c, s))
            # s2 is opaque past the barrier: XLA cannot fold the float
            # multiply-by-zero, so the carry genuinely depends on out
            bump = (s2 * 0.0).astype(carry_arg.dtype)
            return c2 + bump, acc + s2
        final, acc = lax.fori_loop(0, k, body,
                                   (carry_arg, jnp.float32(0.0)))
        return acc

    def empty(carry_arg):
        def body(_, state):
            c, acc = state
            c2, a2 = lax.optimization_barrier((c, acc))
            return c2, a2 + 1.0
        _, acc = lax.fori_loop(0, k, body,
                               (carry_arg, jnp.float32(0.0)))
        return acc

    jfn = jax.jit(chained)
    jempty = jax.jit(empty)
    for _ in range(warmup):
        _sync(jfn(*args))
        _sync(jempty(args[0]))
    best = min(_window(jfn, args) for _ in range(5))
    floor = min(_window(jempty, (args[0],)) for _ in range(5))
    if timed_repeat.debug:
        print(f"#   timed_repeat k={k} best={best*1e3:.2f}ms "
              f"floor={floor*1e3:.2f}ms", flush=True)
    return max(0.0, (best - floor)) / k


timed_repeat.debug = False


def _window(jfn, args) -> float:
    t0 = time.perf_counter()
    _sync(jfn(*args))
    return time.perf_counter() - t0


def measure_step_floor(trainer, ws, staged, n: int = 100) -> float:
    """Per-step dispatch/launch/aliasing floor: a no-op step with the train
    step's exact signature (same dense-state transport, same donation,
    same out_shardings), looped like the bench loop. What remains after
    subtracting real compute stages from the step time is mostly THIS —
    per-program launch cost — and it is a real, measured stage, not a
    fudge residual."""
    from paddlebox_tpu.parallel import mesh as mesh_lib

    repl = mesh_lib.replicated_sharding(trainer.mesh)
    tbl_sh = mesh_lib.table_sharding(trainer.mesh)
    nd = trainer._n_dense_args

    def noop(table, *args):
        labels = args[nd + 3]
        loss = jnp.sum(labels) * 0.0
        return (table, *args[:nd], loss)

    fn = jax.jit(noop, donate_argnums=tuple(range(1 + nd)),
                 out_shardings=(tbl_sh,) + (repl,) * nd + (repl,))
    table = ws.table
    dstate = trainer.pack_dense()
    # the loop donates table/dstate every call; on ANY escape, rebind the
    # caller-visible state to the last arrays that exist so a retry of the
    # surrounding attribution never reads a deleted buffer
    try:
        for _ in range(2):
            out = fn(table, *dstate, *staged)
            table, dstate, loss = out[0], out[1:1 + nd], out[-1]
        _sync(loss)
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                out = fn(table, *dstate, *staged)
                table, dstate, loss = out[0], out[1:1 + nd], out[-1]
            _sync(loss)
            w = time.perf_counter() - t0
            best = w if best is None else min(best, w)
    finally:
        # rebind only live arrays: an execution-time failure donated these
        # away, and unpack_dense on dead buffers would raise inside the
        # finally, masking the real error (state is then genuinely lost —
        # the caller's retry fails fast with 'Array has been deleted')
        if _all_alive(table, dstate):
            ws.table = table
            trainer.params, trainer.opt_state = trainer.unpack_dense(
                dstate)
    return best / n


def _run_step_loop(trainer, fn, staged, n: int, holder: list) -> float:
    """Bench-identical donation loop over holder's [table, dense_state];
    returns sec/step. `holder` is kept current after every step so the
    caller can recover state when a call fails BEFORE executing
    (compile/trace/dispatch errors — the observed transient-tunnel
    class). A failure DURING execution has already consumed holder's
    arrays via donation; the caller's _all_alive guard detects that case
    and recovery is then impossible by design."""
    def step():
        out = fn(holder[0], *holder[1], *staged)
        table, dstate, loss, _, _ = trainer.split_step_out(out)
        holder[0], holder[1] = table, dstate
        return loss

    for _ in range(2):
        loss = step()
    _sync(loss)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            loss = step()
        _sync(loss)
        w = time.perf_counter() - t0
        best = w if best is None else min(best, w)
    return best / n


def _run_defer_loop(trainer, staged, n: int, holder: list,
                    with_apply: bool) -> float:
    """Bench-identical loop over the DEFERRED step program (push_overlap):
    the loss-path program alone (with_apply=False — the table is read,
    never updated; fine for timing) or the real pipeline pair (deferred
    step + apply dispatched back to back, the training loop's dataflow).
    holder carries [table, dense_state] like _run_step_loop."""
    idx, mask, dense, labels = staged[:4]
    plan = staged[4:9]

    def step():
        out = trainer._defer_step_fn(holder[0], *holder[1], *staged)
        dstate, ops, loss, preds, drop = trainer.split_defer_out(out)
        holder[1] = dstate
        if with_apply:
            holder[0] = trainer._apply_fn(holder[0], idx, mask, labels,
                                          *plan, *ops)
        return loss

    for _ in range(2):
        loss = step()
    _sync(loss)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            loss = step()
        _sync(loss)
        w = time.perf_counter() - t0
        best = w if best is None else min(best, w)
    return best / n


def attribute_step(trainer, ws, staged, step_seconds: float,
                   k: int = 24, n_loop: int = 100) -> dict:
    """Stage breakdown of one train step, as device seconds.

    Primary account — **telescoping cumulative ablation**: the trainer
    builds the SAME jitted step with successively more stages replaced by
    shape-preserving no-ops (``Trainer._build_train_step(ablate=...)``,
    biggest stage removed first), each measured with the bench's own
    donation loop. Successive differences sum exactly to the full step,
    so coverage is ~100% by construction; a stage's delta is its marginal
    cost given the stages removed before it (XLA overlaps stages, so
    shared time lands on the earliest-removed stage that exposes it).
    ``glue_residual`` is what the emptied-out step still costs above the
    no-op ``dispatch_floor`` (grad scaling, dense optimizer, psum, output
    plumbing). Isolated per-stage times are reported as ``isolated`` —
    they over-count overlap and bound each stage from above.

    trainer : Trainer in "allreduce" dense-sync mode (the bench config)
    ws      : the PassWorkingSet whose table the step trains
    staged  : one staged batch tuple (idx, mask, dense, labels, *plan)
    step_seconds : measured full-step seconds (the number to attribute)
    """
    from paddlebox_tpu.embedding import sharded

    assert trainer.cfg.dense_sync_mode == "allreduce", (
        "stage attribution instruments the allreduce step")
    idx, mask, dense, labels, *plan = staged
    emb_cfg = trainer.store.cfg
    flat_idx = jnp.asarray(np.asarray(idx).reshape(-1))
    B = idx.shape[0]
    T = trainer.layout.total_len

    # --- telescoping cumulative ablation (primary): remove stages
    # biggest-first; successive differences sum EXACTLY to the full step,
    # so the account is complete by construction. A stage's delta is its
    # marginal cost GIVEN the stages removed before it — shared/overlapped
    # time is charged to the earliest-removed stage that exposes it.
    holder = [ws.table, trainer.pack_dense()]
    times = []
    # every call donates the table; `holder` tracks the newest live arrays
    # and the finally rebinds them, so a transient failure anywhere in the
    # ablation leaves ws/trainer retry-able instead of pointing at deleted
    # buffers (the r3 BENCH loss was a transient error in exactly here).
    # The unablated anchor is measured HERE with the same loop (not taken
    # from the caller): the headline may run k-microbatch supersteps whose
    # per-step time amortizes the dispatch floor, while this account
    # telescopes the SINGLE-step program — the two anchors differ by
    # design and are both reported.
    try:
        for abl in ((), ("push",), ("push", "lookup"),
                    ("push", "lookup", "fwdbwd")):
            # the unablated anchor reuses the already-compiled step
            fn = (trainer._step_fn if not abl
                  else trainer._build_train_step(ablate=abl))
            times.append(_run_step_loop(trainer, fn, staged, n_loop,
                                        holder))
    finally:
        # see measure_step_floor: never rebind donated-away arrays
        if _all_alive(holder):
            ws.table = holder[0]
            trainer.params, trainer.opt_state = trainer.unpack_dense(
                holder[1])
    floor = measure_step_floor(trainer, ws, staged, n=n_loop)
    stages = {
        "sparse_push": times[0] - times[1],
        "lookup": times[1] - times[2],
        "dense_fwd_bwd": times[2] - times[3],
        "glue_residual": times[3] - floor,
        "dispatch_floor": floor,
    }

    # --- deferred-push pipeline A/B (flags.push_overlap): the inline
    # single step vs the real deferred pair (loss-path program + apply
    # program, dispatched back to back like train_pass) and the
    # loss-path program alone — the in-composed-step measurement that
    # keeps the overlap engine choice decision-grade per matrix point.
    overlap_ab = None
    if getattr(trainer, "push_overlap", False) \
            and trainer._defer_step_fn is not None:
        holder = [ws.table, trainer.pack_dense()]
        try:
            t_pair = _run_defer_loop(trainer, staged, n_loop, holder,
                                     with_apply=True)
            t_loss = _run_defer_loop(trainer, staged, n_loop, holder,
                                     with_apply=False)
        finally:
            if _all_alive(holder):
                ws.table = holder[0]
                trainer.params, trainer.opt_state = trainer.unpack_dense(
                    holder[1])
        overlap_ab = {
            "inline_single_step": round(times[0], 6),
            "deferred_step_plus_apply": round(t_pair, 6),
            "deferred_loss_path_step": round(t_loss, 6),
            "note": "pair = both programs dispatched back to back (the "
                    "training loop's dataflow); loss_path = the "
                    "deferred step alone — what the AUC/D2H consumer "
                    "waits on when the apply overlaps the next pack",
        }

    # --- isolated stage times (secondary; shows cross-stage overlap) ---
    # fused-pull trainers measure the stages the fused step actually
    # runs: gather-pool pull, pooled model fwd/bwd, and the pooled-
    # cotangent expansion inside the push window — so the mh4d32/d128
    # matrix attributions name the fused stages, not the unfused ones.
    table, params = ws.table, trainer.params
    import optax
    from paddlebox_tpu.ops.seqpool_cvm import PooledSlots
    model = trainer.model
    seg = trainer.layout.segment_ids
    num_slots = trainer.layout.num_slots
    fused_pull = (getattr(trainer, "pull_engine", "gather_seqpool")
                  == "fused_gather_pool")
    mask_dev = jnp.asarray(np.asarray(mask))
    shows0 = jnp.asarray(np.asarray(mask).reshape(-1).astype(np.float32))
    clks0 = jnp.zeros_like(shows0)
    plan_t = tuple(plan) if plan and plan[0].shape[0] else None

    if fused_pull:
        L_hot = T // num_slots
        idx_dev = jnp.asarray(np.asarray(idx))

        def lookup_fn(fidx2, tbl):
            return sharded.fused_pull_pool(tbl, fidx2, emb_cfg,
                                           num_slots, L_hot)

        isolated = {"lookup": timed_repeat(lookup_fn, (idx_dev, table),
                                           k=k)}
        pooled0 = jax.jit(lookup_fn)(idx_dev, table)

        def fwdbwd(pooled, p):
            def loss_fn(pp, pin):
                logits = model.apply(pp, PooledSlots(pin), mask, dense,
                                     seg, num_slots)
                return jnp.mean(
                    optax.sigmoid_binary_cross_entropy(logits, labels))
            _, (gp, gpooled) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(p, pooled)
            return gpooled

        isolated["dense_fwd_bwd"] = timed_repeat(fwdbwd,
                                                 (pooled0, params), k=k)
        gpooled0 = jax.jit(fwdbwd)(pooled0, params)

        def push_fn(gpool, tbl):
            sg = sharded.pooled_grad_tokens(gpool, mask_dev, seg,
                                            num_slots)
            return sharded.push(tbl, flat_idx, sg, shows0, clks0,
                                emb_cfg, plan=plan_t)

        isolated["sparse_push"] = timed_repeat(push_fn, (gpooled0, table),
                                               k=k)
    else:
        def lookup_fn(fidx, tbl):
            return sharded.lookup(tbl, fidx, emb_cfg).reshape(
                B, T, emb_cfg.pull_width)

        isolated = {"lookup": timed_repeat(lookup_fn, (flat_idx, table),
                                           k=k)}
        pulled0 = jax.jit(lookup_fn)(flat_idx, table)

        def fwdbwd(pulled, p):
            def loss_fn(pp, pin):
                logits = model.apply(pp, pin, mask, dense, seg, num_slots)
                return jnp.mean(
                    optax.sigmoid_binary_cross_entropy(logits, labels))
            _, (gp, gpull) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                p, pulled)
            return gpull

        isolated["dense_fwd_bwd"] = timed_repeat(fwdbwd, (pulled0, params),
                                                 k=k)
        gpull0 = jax.jit(fwdbwd)(pulled0, params)
        sgrad0 = jax.jit(
            lambda g: g[..., 2:].reshape(-1, emb_cfg.grad_width))(gpull0)

        def push_fn(sg, tbl):
            return sharded.push(tbl, flat_idx, sg, shows0, clks0, emb_cfg,
                                plan=plan_t)

        isolated["sparse_push"] = timed_repeat(push_fn, (sgrad0, table),
                                               k=k)

    attributed = float(sum(stages.values()))
    single = times[0]
    return {
        "stages": {n: round(s, 6) for n, s in stages.items()},
        "isolated": {n: round(s, 6) for n, s in isolated.items()},
        "push_overlap": ("on" if getattr(trainer, "push_overlap", False)
                         else "off"),
        "overlap_ab": overlap_ab,
        "attributed_seconds": round(attributed, 6),
        "single_step_seconds": round(single, 6),
        "headline_step_seconds": round(step_seconds, 6),
        "unattributed_seconds": round(single - attributed, 6),
        "coverage": round(attributed / single, 3) if single else 0.0,
        "method_overlap": "overlap_ab (when push_overlap is on) A/Bs the "
                  "inline step against the deferred step+apply pair in "
                  "the real programs",
        "method": "stages = telescoping cumulative ablation of the "
                  "SINGLE-step program (full -> -push -> -push-lookup "
                  "-> -push-lookup-fwdbwd -> no-op floor, bench-"
                  "identical donation loops; differences sum to the "
                  "measured single step). headline_step_seconds is the "
                  "bench's per-step time and amortizes the dispatch "
                  "floor over steps_per_dispatch microbatches, so it "
                  "can sit below the single-step anchor. isolated = "
                  "each stage repeated in one jit (over-counts XLA "
                  "overlap); device_get-terminated windows",
    }


# ---------------------------------------------------------------------------
# Sparse-push floor analysis: what the push SHOULD cost on this hardware.
#
# The stage attribution says what the push DOES cost; this derives the
# analytic floor of each push sub-stage so a regression alarms against a
# floor, not just against the chip's headline peaks (an 11ms push can pass
# an MFU audit while sitting 10x above its own physics). Stages mirror the
# binned-push pipeline: plan-H2D (host plan staging — rides the pack
# pipeline, NOT on the step's critical path), kernel DMA (packed-operand
# build + the kernel's tile streams), one-hot dots (the MXU merge), and
# the fused table update (one bandwidth pass over the table). Scatter-
# engine widths (no kernel geometry) get the scatter's bandwidth model.
# ---------------------------------------------------------------------------

def push_floor_analysis(emb_cfg, n_rows: int, tokens: int,
                        n_split: int = 2, peaks=None,
                        measured_push: float | None = None,
                        slack: float = 3.0, premerged: bool = False,
                        table_width: int | None = None,
                        unique_lanes: int | None = None) -> dict:
    """Per-stage analytic bounds of one sparse push + closure statements.

    peaks : (peak_bf16_flops, peak_hbm_bytes) or None (unknown hardware —
            bounds are reported as bytes/FLOPs only, closure abstains).
    measured_push : the attribution's sparse_push seconds, if available.
    premerged / table_width : the lane contract + physical table width
            the engine resolver keys on — pass what the step compiled
            with so `engine` names the real code path.
    unique_lanes : rows the premerged lanes actually touch (defaults to
            tokens — an upper bound; the fused engine's floor scales
            with THIS, which is the whole point of that engine).
    closed : True when the measured push sits within `slack` x the
            active engine's floor; otherwise a reason string naming the
            gap — the alarm line. `engines` carries the same statement
            per CANDIDATE engine at this geometry, so a non-closed
            point names the concrete flags.push_engine to force
            (best_engine) instead of a bare alarm.
    """
    from paddlebox_tpu.ops import pallas_kernels as pk

    geom = pk._bp_geometry(emb_cfg, n_rows)
    storage_f32 = emb_cfg.storage == "f32"
    width = int(table_width) if table_width is not None \
        else emb_cfg.row_width
    # THE resolver names the engine the step actually compiles with
    # (the same call the bench's per-point push_engine record makes)
    engine = pk.resolve_push_engine(emb_cfg, n_rows, premerged=premerged,
                                    storage_f32=storage_f32,
                                    table_width=width)
    gw = emb_cfg.grad_width
    rw = emb_cfg.row_width
    lanes = int(unique_lanes) if unique_lanes is not None else tokens
    peak_f, peak_b = peaks if peaks is not None else (None, None)

    def _bw_stage(nbytes, note):
        return {"bytes": int(nbytes),
                "bound_seconds": (round(nbytes / peak_b, 6)
                                  if peak_b else None),
                "note": note}

    def _engine_stages(name):
        """The three floor stages (constant keys across engines) for one
        candidate engine at this geometry, or None when the engine
        cannot engage here."""
        st: dict = {}
        if name == "binned_kernel":
            if geom is None:
                return None
            P, PP, G, SB = geom
            W = -(-(PP + 2) // 128) * 128
            TILE = pk._bp_tile(SB, G)
            RB = SB // G
            AW = pk._bp_acc_width(G, PP)
            tok_pad = tokens + TILE
            st["kernel_dma"] = _bw_stage(
                tok_pad * W * 4 * 2          # packed build write + DMA read
                + (n_rows // SB) * RB * AW * 4,   # grouped acc write
                "packed-operand build + double-buffered tile DMA + acc "
                "write")
            dot_flops = 2.0 * n_split * tokens * RB * AW
            st["onehot_dots"] = {
                "flops": dot_flops,
                "bound_seconds": (round(dot_flops / peak_f, 6)
                                  if peak_f else None),
                "note": f"{n_split}-plane one-hot MXU merge, RB={RB} "
                        f"AW={AW}"}
            st["fused_update"] = _bw_stage(
                n_rows * (rw * 4 * 2 + PP * 4),
                "one full-width XLA pass: table read+write + acc read")
            return st
        if name == "scatter_accumulate":
            if not storage_f32 \
                    or pk.scatter_accumulate_geometry(n_rows, width) \
                    is None:
                return None
            st["kernel_dma"] = _bw_stage(
                lanes * (width * 4 * 2 + (gw + 3) * 4),
                f"per-unique-row DMA read + write-back at the physical "
                f"table width ({width} lanes) + merged payload read — "
                f"{lanes} lanes, O(unique rows), no full-table term")
            st["onehot_dots"] = {
                "flops": 0.0,
                "bound_seconds": 0.0 if peak_b else None,
                "note": "fused engine — row-wise VMEM update, no MXU "
                        "merge"}
            st["fused_update"] = _bw_stage(
                0,
                "optimizer applied in-kernel on the gathered rows — the "
                "O(table) update pass never runs")
            return st
        st["kernel_dma"] = _bw_stage(
            tokens * (gw + 3) * 4 * 2,
            "scatter payload write + read (XLA scatter engine)")
        st["onehot_dots"] = {
            "flops": 0.0, "bound_seconds": 0.0 if peak_b else None,
            "note": "scatter engine — no MXU merge"}
        st["fused_update"] = _bw_stage(
            n_rows * (rw * 4 * 2 + (gw + 3) * 4 * 2),
            "scatter-add accumulate + fused update pass over the table")
        return st

    def _floor_of(st):
        bounded = [s["bound_seconds"] for s in st.values()]
        return (round(sum(b for b in bounded if b is not None), 6)
                if any(b is not None for b in bounded) else None)

    stages = _engine_stages(engine)
    assert stages is not None, engine    # the resolver only names engageable engines
    # plan staging: order + block windows (+ dedup lanes at worst)
    stages = {"plan_h2d": {
        "bytes": tokens * 4 * 3 + 1024,
        "bound_seconds": None,
        "note": "host plan staged by the pack pipeline, overlapped with "
                "device compute — off the step's critical path; counted "
                "for completeness, excluded from the floor",
    }, **stages}
    # candidate-engine floors: every engine that COULD engage at this
    # geometry gets its own bound, so the closure statements below can
    # name the concrete engine to force when the active one is off its
    # physics (the doctor's push-floor rule consumes exactly this)
    engines: dict = {}
    for name in pk.PUSH_ENGINES:
        st = _engine_stages(name)
        if st is None:
            continue
        e = {"floor_seconds": _floor_of(st)}
        if name == "scatter_accumulate" and not premerged:
            e["note"] = ("requires premerged unique lanes "
                         "(flags.push_dedup_premerge)")
        if name == "binned_kernel":
            from paddlebox_tpu.config import flags as _flags
            if not _flags.binned_push:
                # auto skips it while the enable knob is off; a forced
                # flags.push_engine=binned_kernel bypasses the knob
                e["note"] = ("flags.binned_push is off — engages only "
                             "when forced")
        engines[name] = e
    out = {
        "engine": engine,
        "premerged": bool(premerged),
        "tokens": tokens,
        "unique_lanes": lanes,
        "table_rows": n_rows,
        "stages": stages,
        "floor_seconds": _floor_of(
            {k: v for k, v in stages.items() if k != "plan_h2d"}),
        "engines": engines,
        "measured_push_seconds": (round(measured_push, 6)
                                  if measured_push is not None else None),
    }
    finalize_push_floor(out, measured_push, slack)
    return out


def finalize_push_floor(floor: dict, measured_push: float | None,
                        slack: float = 3.0) -> None:
    """(Re)close a push_floor_analysis result once the attribution has
    measured the real push stage — mutates `floor` in place (the bench
    computes the floor before attribution runs and finalizes after).
    Closes the active engine's statement AND the per-candidate-engine
    statements, and names `best_engine` — the lowest-floor candidate —
    so an off-floor point suggests a concrete flags.push_engine force.
    """
    f = floor.get("floor_seconds")
    if measured_push is not None:
        floor["measured_push_seconds"] = round(measured_push, 6)

    def _close(bound, label):
        if bound is None:
            return "no peak table for this hardware (CPU smoke?)"
        if measured_push is None:
            return "no measured push stage (attribution absent)"
        if measured_push <= slack * max(bound, 1e-9):
            return True
        return (f"measured {measured_push*1e3:.2f}ms > {slack:.0f}x "
                f"{label} {bound*1e3:.2f}ms")

    closed = _close(f, "floor")
    floor["closed"] = (closed if closed is True or f is None
                       or measured_push is None else closed +
                       " — push is off its physics; check the pack "
                       "engine and plan staging before trusting the "
                       "step")
    engines = floor.get("engines") or {}
    best = None
    for name, e in engines.items():
        e["closed"] = _close(e.get("floor_seconds"),
                             f"{name} floor")
        fs = e.get("floor_seconds")
        if fs is not None and (best is None
                               or fs < engines[best]["floor_seconds"]):
            best = name
    if best is not None:
        floor["best_engine"] = best
