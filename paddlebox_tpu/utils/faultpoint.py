"""Named fault-injection points for the crash-safe checkpoint subsystem.

The reference survives worker preemption by pass-granularity restart
(SURVEY.md §5 "Failure detection": load the newest base + replay delta
donefiles). Proving our atomic-manifest/resume path actually delivers that
needs a way to die at *specific* instructions — mid dense write, between a
delta file and its manifest commit, inside the feed-pass flush — not
wherever a SIGKILL happens to land. This registry is that harness.

Every interesting crash window in the save/flush/apply paths calls
:func:`hit` with a registered name. Disarmed (the default), a hit is one
global ``is None`` check — nothing to measure. Armed — via :func:`arm` in
process, or the environment for subprocess tests::

    PBTPU_FAULTPOINT=store.save_delta.pre_manifest   # point name(s), comma-ok
    PBTPU_FAULTPOINT_ACTION=kill                     # kill | ioerror
    PBTPU_FAULTPOINT_AFTER=2                         # fire on the 3rd hit

Several points may be armed at once (comma-separated names in the
environment, or a list to :func:`arm`): compound-failure kill matrices —
a joiner dying while an incumbent's spill write-back faults — arm each
leg independently and every armed point keeps its own hit counter.

— the named point either hard-kills the process (``os._exit(137)``, the
closest in-process stand-in for SIGKILL/preemption: no atexit handlers, no
finally blocks, buffers lost) or raises :class:`FaultInjected` (an OSError,
for exercising IO-error retry/cleanup paths without losing the process).

``POINTS`` is the closed registry; tests parametrize over it so a new
crash window cannot be added without the kill→resume matrix covering it.
``hit()`` refuses unregistered names for the same reason.
"""

from __future__ import annotations

import os

# The closed set of registered crash windows. Keep in sync with the
# kill→resume matrix in tests/test_crash_safety.py (it parametrizes over
# this tuple) and the PARITY.md table.
POINTS: tuple[str, ...] = (
    # utils/checkpoint.save_pytree: dense tmp file fully written + fsynced,
    # os.replace not yet executed — the final name must still hold the
    # previous snapshot (or nothing).
    "ckpt.dense.pre_replace",
    # embedding/store.save_base: base.npz tmp written, before the replace.
    "store.save_base.pre_replace",
    # embedding/store.save_delta: delta-*.npz tmp written, before replace.
    "store.save_delta.pre_replace",
    # embedding/store.save_delta: delta file landed, manifest commit not
    # yet — the chain manifest must still describe the previous save.
    "store.save_delta.pre_manifest",
    # embedding/feed_pass.flush: unsynced device rows are about to move
    # D2H into the host store (the materialization that precedes every
    # save) — dying here must leave the previous snapshot untouched.
    "feed_pass.flush.pre",
    # embedding/feed_pass._stage/_apply_patch: the incremental delta
    # feed is about to fetch fresh/stale rows from the host store (or
    # patch a background staging with rows mutated after it) — the
    # boundary work of pass N+1. A kill mid-delta-stage must resume to
    # the exact state a full rebuild would produce: nothing is applied
    # yet, so the previous pass's snapshot is the recovery point.
    "feed_pass.delta_stage.pre",
    # train/trainer._dispatch_pending_apply: a deferred sparse-push apply
    # (flags.push_overlap) is about to dispatch mid-pass.
    "trainer.push_apply.pre",
    # utils/pass_ckpt.save: all planes written, snapshot MANIFEST.json not
    # yet committed — the snapshot must be invisible to resume.
    "pass_ckpt.pre_manifest",
    # utils/pass_ckpt.save: manifest committed — resume must land on THIS
    # snapshot.
    "pass_ckpt.post_manifest",
    # train/trainer._midpass_save: a MID-pass snapshot just committed —
    # dying here must resume from the dataset/shuffle cursor (skip the
    # already-trained steps), not replay the pass from its start.
    "trainer.midpass.post_save",
    # remote (hdfs://) checkpoint roots: local snapshot committed, upload
    # not yet run — the remote donefile must still name only fully
    # uploaded snapshots (pass_ckpt remote mirror + FleetUtil._save_dir).
    "remote_ckpt.upload.pre",
    # remote restore: about to download a snapshot/model dir — dying here
    # must leave the next resume able to re-download from the donefile.
    "remote_ckpt.download.pre",
    # train/trainer._pack_host: a batch's translate/plan is about to run
    # on the pack-pipeline thread — a mid-pass kill in the host pipeline
    # (the "pack" phase of the elastic kill matrix; also a plain
    # kill→resume window).
    "trainer.pack.pre",
    # train/trainer train loop: the jitted step for this batch is about
    # to dispatch — the tightest mid-pass kill window (elastic "step
    # dispatch" phase; also a plain kill→resume window).
    "trainer.step.pre",
    # distributed/resilience ElasticWorld._attempt: the re-formation
    # window itself. pre_arrive = drained + snapshotted, about to join
    # the epoch; post_seal = membership sealed/read, ack not yet sent;
    # post_ack = acked, peers may or may not have completed — a kill at
    # any of these must leave the survivors converging on ONE generation
    # (the next one, without this rank), never a mixed world.
    "elastic.reform.pre_arrive",
    "elastic.reform.post_seal",
    "elastic.reform.post_ack",
    # serving/publisher.publish: the three windows of the model-publish
    # protocol (ISSUE 7). pre_manifest = artifact members written, its
    # MANIFEST.json not yet committed — the version must be invisible;
    # pre_upload = local artifact committed, remote upload not yet run —
    # the remote root may hold a torn copy but the donefile must not
    # name it; pre_donefile = upload verified, the announce line not yet
    # appended — the serving side must simply never see this version
    # (the re-publish after resume re-lands it). A kill at ANY of these
    # must leave every ANNOUNCED version fully verifiable: a torn
    # publish must never serve.
    "serving.publish.pre_manifest",
    "serving.publish.pre_upload",
    "serving.publish.pre_donefile",
    # sharded embedding exchange (ISSUE 10). exchange.store.* are the
    # ShardedEmbeddingStore's save windows: pre_shard_save = about to
    # write one shard's chain (earlier shards' files landed, the
    # top-level shards.json still describes the previous save);
    # pre_manifest = every shard saved, the top manifest not yet
    # committed. A kill at either must roll the WHOLE save back — the
    # restore replays each shard to the last committed manifest's seqs
    # and the orphaned newer files are overwritten by the re-run.
    "exchange.store.pre_shard_save",
    "exchange.store.pre_manifest",
    # trainer eval-overflow retry: a routed eval pass dropped tokens and
    # is about to re-run at the grown capacity factor — dying here must
    # leave nothing half-applied (eval is stateless; the point exists so
    # the never-silent overflow retry path is ioerror-exercisable).
    "exchange.eval.pre_retry",
    # tiered-table spill stores (ISSUE 11). tiering.save.pre_flush = a
    # spill-backed store is about to msync its memory-mapped row plane
    # and stream it into a base/delta payload (the window where the
    # on-disk spill file and the checkpoint-in-progress could diverge) —
    # dying here must leave the chain at the previous committed save.
    # tiering.evict.pre = the pass-boundary RAM-tier re-evaluation is
    # about to demote cold cached rows; the cache is never authoritative,
    # so a kill here must resume bit-exact. Both run in the main kill
    # matrix under PBTPU_TABLE_TIERING=spill (sharded spill sub-stores).
    "tiering.save.pre_flush",
    "tiering.evict.pre",
    # monitor/sinks.JsonlSink._rotate (ISSUE 12): the telemetry writer
    # thread is about to close a full JSONL segment and open its numbered
    # successor. An injected failure here must latch the sink's error
    # (telemetry stops, training does NOT — the hub's isolation contract)
    # and leave every already-written segment schema-clean; covered
    # in-process by tests/test_doctor.py, not by the kill matrices
    # (rotation never fires in the crash workers' small streams).
    "telemetry.rotate.pre",
    # distributed/resilience ElasticWorld.admit (ISSUE 18): the elastic
    # GROW windows. pre_register = the joiner is about to CAS-register
    # its admit request against the sealed generation; post_ack = the
    # joiner acked a generation that includes it, incumbents may or may
    # not have completed — a kill at either must leave the incumbents
    # converging on one generation (with or without the joiner, never a
    # mixed world) and the next admit attempt able to join cleanly.
    "elastic.admit.pre_register",
    "elastic.admit.post_ack",
    # train/trainer.set_shard_ownership: the per-host build partition is
    # about to rebind after an elastic resize — the newcomer (or a
    # shrunk survivor) is about to start rebuilding exactly its shards'
    # working set. A kill here (joiner mid-shard-rebuild, or an
    # incumbent mid-rebind) must leave the surviving generation
    # trainable and bit-consistent.
    "elastic.ownership.rebind.pre",
    # serving/fleet.py + serving/router.py (ISSUE 20): the serving-fleet
    # crash windows. lease.pre_verify = a replica holds the shared-
    # staging download lease, the artifact bytes are staged but the CRC
    # verify + atomic rename have not run — dying here must leave the
    # lease expirable so a peer replica retakes it, and the host must
    # end with exactly ONE verified staging copy (never a torn copy
    # under the final name). replica.pre_build = a replica is about to
    # build/apply a fetched version (the hot-swap window) — a kill here
    # must drop only that replica: the router routes around it and the
    # supervisor restarts it with backoff. router.pre_dispatch = a
    # scoring request is about to dispatch to a chosen replica — the
    # ioerror leg of the router's retry-on-another-replica contract.
    "serving.fleet.lease.pre_verify",
    "serving.fleet.replica.pre_build",
    "serving.fleet.router.pre_dispatch",
)

# Points that fire only inside the elastic re-formation window: the
# single-host and plain multi-host kill→resume matrices never reach them
# (no reform happens there) — they are covered by the elastic kill matrix
# (tests/test_elastic.py) instead.
ELASTIC_POINTS: tuple[str, ...] = (
    "elastic.reform.pre_arrive",
    "elastic.reform.post_seal",
    "elastic.reform.post_ack",
)

# Points that fire only inside the elastic ADMIT (world-grow) window:
# nothing in the shrink-only matrices ever calls ElasticWorld.admit or
# rebinds ownership onto a grown world — they are covered by the grow
# kill matrix (tests/test_elastic.py + tests/grow_worker.py) instead.
ADMIT_POINTS: tuple[str, ...] = (
    "elastic.admit.pre_register",
    "elastic.admit.post_ack",
    "elastic.ownership.rebind.pre",
)

# Points that fire only inside the serving publish path: the training
# kill→resume matrices never publish a serving model — they are covered
# by the publish/swap kill matrix (tests/test_serving.py) instead, which
# carries its own closed-registry guard.
SERVING_POINTS: tuple[str, ...] = (
    "serving.publish.pre_manifest",
    "serving.publish.pre_upload",
    "serving.publish.pre_donefile",
)

# Points that fire only inside the sharded-exchange subsystem (the
# ShardedEmbeddingStore save path and the trainer's eval-overflow
# retry): the single-host training kill→resume matrix never saves a
# sharded host store or drops routed tokens — they are covered by
# tests/test_exchange.py instead.
EXCHANGE_POINTS: tuple[str, ...] = (
    "exchange.store.pre_shard_save",
    "exchange.store.pre_manifest",
    "exchange.eval.pre_retry",
)

# Points that fire only inside the telemetry plane (the JSONL writer
# thread): the kill→resume matrices never rotate an event stream, and a
# telemetry fault must by contract never perturb training state — they
# are covered by the ioerror tests in tests/test_doctor.py instead.
MONITOR_POINTS: tuple[str, ...] = (
    "telemetry.rotate.pre",
)

# Points that fire only inside the serving FLEET (replica supervision,
# shared staging, router dispatch): the training kill→resume matrices
# never run a replica fleet — they are covered by the fleet kill matrix
# (tests/test_fleet.py) instead, which carries its own closed-registry
# guard (all names prefixed "serving.fleet.").
FLEET_POINTS: tuple[str, ...] = (
    "serving.fleet.lease.pre_verify",
    "serving.fleet.replica.pre_build",
    "serving.fleet.router.pre_dispatch",
)


class FaultInjected(OSError):
    """Raised by an armed ``ioerror`` fault point."""


class _Armed:
    __slots__ = ("name", "action", "after", "hits")

    def __init__(self, name: str, action: str, after: int):
        self.name = name
        self.action = action
        self.after = after
        self.hits = 0


# armed points by name: multiple points may be live at once, so compound
# failures (a joiner dying while an incumbent's spill write-back faults)
# are expressible in one kill matrix entry
_armed: dict[str, _Armed] = {}
# per-point hit counters, kept even when disarmed is re-armed (observability
# for tests asserting a point is actually on the executed path)
_counts: dict[str, int] = {}


def arm(name, action: str = "kill", after: int = 0) -> None:
    """Arm one or more fault points concurrently. ``name`` is a point
    name, a comma-separated list of names, or a list/tuple of names — all
    armed with the same ``action``/``after`` (arm() again for per-point
    settings; a re-arm of a live name resets its hit count). ``action``:
    ``kill`` (os._exit(137)) or ``ioerror`` (raise FaultInjected).
    ``after``: fire on hit #after+1."""
    names = ([n.strip() for n in name.split(",") if n.strip()]
             if isinstance(name, str) else [str(n) for n in name])
    if not names:
        raise ValueError("arm() needs at least one fault point name")
    for n in names:
        if n not in POINTS:
            raise KeyError(
                f"unknown fault point {n!r}; registered: {POINTS}")
    if action not in ("kill", "ioerror"):
        raise ValueError(f"fault action {action!r} (want kill|ioerror)")
    for n in names:
        _armed[n] = _Armed(n, action, int(after))
    try:
        from paddlebox_tpu.monitor.hub import _HUB
        for n in names:
            _HUB.counter_add("faultpoint.armed")
            _HUB.event("faultpoint_armed", point=n, action=action,
                       after=int(after))
    # pblint: disable=silent-except -- observability must not mask the
    # harness: a broken hub cannot be allowed to fail arm() itself
    except Exception:
        pass


def disarm(name: str | None = None) -> None:
    """Disarm one point (by name) or, with no argument, all of them."""
    if name is None:
        _armed.clear()
    else:
        _armed.pop(name, None)


def armed_points() -> tuple[str, ...]:
    """Names currently armed (observability for harness assertions)."""
    return tuple(sorted(_armed))


def hit_count(name: str) -> int:
    return _counts.get(name, 0)


def hit(name: str) -> None:
    """Mark a registered crash window. No-op unless armed on this name."""
    if not _armed:
        return
    if name not in POINTS:
        raise KeyError(f"unregistered fault point {name!r}")
    _counts[name] = _counts.get(name, 0) + 1
    a = _armed.get(name)
    if a is None:
        return
    a.hits += 1
    if a.hits <= a.after:
        return
    # telemetry before firing (the kill path loses in-flight sinks by
    # design — that IS the crash being modeled; counters still register
    # for the ioerror action and in the parent of subprocess tests)
    try:
        from paddlebox_tpu.monitor.hub import _HUB
        _HUB.counter_add("faultpoint.trips")
        _HUB.counter_add(f"faultpoint.trip.{name}")
        _HUB.event("faultpoint_trip", point=name, action=a.action)
    # pblint: disable=silent-except -- observability must not mask the
    # fault being injected: the kill/ioerror below IS the product here
    except Exception:
        pass
    if a.action == "kill":
        # stderr marker first: the harness asserts the kill came from the
        # armed point, not an incidental crash
        os.write(2, f"FAULTPOINT KILL {name}\n".encode())
        os._exit(137)
    raise FaultInjected(f"fault point {name} (injected)")


def _arm_from_env() -> None:
    spec = os.environ.get("PBTPU_FAULTPOINT", "")
    if not spec:
        return
    names = [n.strip() for n in spec.split(",") if n.strip()]
    actions = [a.strip() for a in
               os.environ.get("PBTPU_FAULTPOINT_ACTION", "kill").split(",")]
    afters = [a.strip() for a in
              os.environ.get("PBTPU_FAULTPOINT_AFTER", "0").split(",")]
    # a single action/after applies to every name; otherwise the lists
    # align positionally with the comma-separated point names
    for i, n in enumerate(names):
        action = actions[i] if len(actions) > 1 else actions[0]
        after = afters[i] if len(afters) > 1 else afters[0]
        arm(n, action, int(after))


_arm_from_env()
