"""Dense-parameter checkpointing — pytree ↔ npz — and the atomic-write /
manifest primitives every snapshot writer in the system goes through.

The reference persists dense params by copying the thread-0 scope back to the
root scope at trainer Finalize (boxps_trainer.cc:123-131) and then calling
``fluid.io.save_persistables``. Here the dense state is a JAX pytree
(params + optimizer state); we serialize it keyed by tree path so load is
order-independent and shape-checked.

Crash-safety contract (the pass/day training loop restarts from these
files after preemption — SURVEY.md §5 "Failure detection"):

- Writers go write-tmp → fsync → ``os.replace`` (:func:`atomic_file`), so a
  file is either the complete previous version or the complete new version
  under its final name — never a truncation.
- Snapshot directories carry a ``MANIFEST.json`` (:func:`write_manifest`)
  listing every member with size + CRC32; :func:`verify_manifest` re-hashes
  and raises :class:`CheckpointCorruptError` naming the first bad member,
  so a torn snapshot is *diagnosed*, not silently half-loaded.
"""

from __future__ import annotations

import json
import os
import zipfile
import zlib
from contextlib import contextmanager
from typing import Any

import jax
import numpy as np

from paddlebox_tpu.utils import faultpoint

MANIFEST_NAME = "MANIFEST.json"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint member is truncated/corrupt (bad zip, bad CRC, bad
    size, missing file). Carries the offending path in ``fname``."""

    def __init__(self, fname: str, detail: str):
        super().__init__(f"checkpoint {fname!r} is corrupt or truncated: "
                         f"{detail}")
        self.fname = fname


# ---------------------------------------------------------------------------
# atomic durable writes
# ---------------------------------------------------------------------------

@contextmanager
def atomic_file(path: str, fault_point: str | None = None):
    """Yield a temp path in ``path``'s directory; on clean exit fsync it and
    ``os.replace`` onto ``path`` (then fsync the directory so the rename
    itself is durable). On exception the temp file is removed and ``path``
    is untouched — a crashed writer can never leave a partial file under
    the final name.

    ``fault_point``: optional faultpoint name hit between the durable tmp
    write and the rename — the window the atomicity claim is about.
    """
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        yield tmp
        with open(tmp, "rb+") as f:
            f.flush()
            os.fsync(f.fileno())
        if fault_point is not None:
            faultpoint.hit(fault_point)
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        # pblint: disable=silent-except -- unwind-path hygiene: the
        # original exception is re-raised below and must not be masked
        # by a failed tmp cleanup (worst case: an orphan .tmp file)
        except OSError:
            pass
        raise


def _fsync_dir(d: str) -> None:
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:          # platform without directory fds
        return
    try:
        os.fsync(fd)
    # pblint: disable=silent-except -- directory fsync is best-effort
    # durability hardening: some filesystems (and all of macOS) reject
    # fsync on directory fds; the file's own fsync already committed
    except OSError:
        pass
    finally:
        os.close(fd)


def crc32_file(path: str, chunk: int = 1 << 20) -> int:
    import time

    from paddlebox_tpu.monitor import counter_add
    t0 = time.perf_counter()
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                # checksum cost is part of the checkpoint budget the
                # flight record accounts (save + verify both land here)
                counter_add("ckpt.crc_seconds", time.perf_counter() - t0)
                counter_add("ckpt.crc_files")
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(b, crc)


def file_entry(path: str) -> dict[str, int]:
    """Manifest entry for one on-disk member: {bytes, crc32}."""
    return {"bytes": os.path.getsize(path), "crc32": crc32_file(path)}


# ---------------------------------------------------------------------------
# manifests
# ---------------------------------------------------------------------------

def write_manifest(dirpath: str, files: dict[str, dict],
                   fault_point: str | None = None, **meta: Any) -> str:
    """Atomically commit ``MANIFEST.json`` for a snapshot directory.

    ``files`` maps member-relative-path → ``file_entry`` dict. Extra
    keyword metadata (pass_id, save_seq, chain parent, …) is stored
    alongside. The manifest lands LAST, atomically — its presence is the
    snapshot's commit record; a snapshot without one never existed.
    """
    out = os.path.join(dirpath, MANIFEST_NAME)
    doc = dict(meta)
    doc["files"] = files
    with atomic_file(out, fault_point=fault_point) as tmp:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
    return out


def read_manifest(dirpath: str) -> dict | None:
    p = os.path.join(dirpath, MANIFEST_NAME)
    if not os.path.exists(p):
        return None
    try:
        with open(p) as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        raise CheckpointCorruptError(p, f"unreadable manifest ({e})")


def verify_manifest(dirpath: str, manifest: dict | None = None,
                    only: list[str] | None = None) -> dict:
    """Re-hash the members listed in ``dirpath``'s manifest; raise
    :class:`CheckpointCorruptError` on the first missing/short/mismatched
    member, naming it. Returns the (parsed) manifest. ``only`` restricts
    verification to a subset of members (e.g. the delta chain prefix a
    resume actually replays)."""
    m = manifest if manifest is not None else read_manifest(dirpath)
    if m is None:
        raise CheckpointCorruptError(
            os.path.join(dirpath, MANIFEST_NAME),
            "missing manifest (snapshot was never committed)")
    names = only if only is not None else list(m.get("files", {}))
    for name in names:
        ent = m["files"].get(name)
        p = os.path.join(dirpath, name)
        if ent is None:
            raise CheckpointCorruptError(p, "member absent from manifest")
        if not os.path.exists(p):
            raise CheckpointCorruptError(p, "member file missing on disk")
        size = os.path.getsize(p)
        if size != ent["bytes"]:
            raise CheckpointCorruptError(
                p, f"size {size} != manifest {ent['bytes']} "
                   f"(truncated or torn write)")
        crc = crc32_file(p)
        if crc != ent["crc32"]:
            raise CheckpointCorruptError(
                p, f"crc32 {crc:#010x} != manifest {ent['crc32']:#010x}")
    return m


# ---------------------------------------------------------------------------
# dense pytree ↔ npz
# ---------------------------------------------------------------------------

def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def save_pytree(tree: Any, fname: str, compress: bool = True) -> str:
    """compress=False writes STORED zip members (plain .npy bytes at a
    fixed offset) so non-Python clients can mmap the arrays directly —
    the serving export uses this (native/serving_score.c).

    The write is atomic-durable: bytes go to a same-directory temp file,
    fsync, then ``os.replace`` — a reader (or a resume after SIGKILL mid-
    write) sees the previous complete file or the new complete file,
    never a truncation under the final name."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    # force C order: XLA may hand back an F-contiguous view of its chosen
    # device layout, and np.save would then write fortran_order=True —
    # which the mmap-based C serving client (serving_score.c) rejects.
    # (order="C", not ascontiguousarray: the latter promotes 0-d leaves
    # like adam's count to (1,), breaking load_pytree's shape check)
    arrays = {_path_str(path): np.asarray(leaf, order="C")
              for path, leaf in leaves}
    with atomic_file(fname, fault_point="ckpt.dense.pre_replace") as tmp:
        # write through an open handle: np.savez would append ".npz" to a
        # bare path, breaking the tmp → final rename pairing
        with open(tmp, "wb") as f:
            (np.savez_compressed if compress else np.savez)(f, **arrays)
    return fname


def load_pytree(template: Any, fname: str) -> Any:
    """Load into the structure of `template` (shapes must match).

    The npz handle is closed on every path (context manager), and a
    truncated/corrupt archive surfaces as :class:`CheckpointCorruptError`
    naming the file — the resume path keys its fallback on that."""
    try:
        ctx = np.load(fname)
    except (zipfile.BadZipFile, EOFError, ValueError) as e:
        raise CheckpointCorruptError(fname, str(e))
    except OSError as e:
        if not os.path.exists(fname):
            raise
        raise CheckpointCorruptError(fname, str(e))
    with ctx as z:
        leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
        out = []
        for path, leaf in leaves:
            key = _path_str(path)
            if key not in z:
                raise KeyError(f"checkpoint {fname} missing leaf {key!r}")
            try:
                arr = z[key]
            except (zipfile.BadZipFile, EOFError, zlib.error,
                    ValueError) as e:
                raise CheckpointCorruptError(
                    fname, f"member {key!r} unreadable ({e})")
            want = np.shape(leaf)
            if tuple(arr.shape) != tuple(want):
                raise ValueError(
                    f"leaf {key!r}: checkpoint shape {arr.shape} != {want}")
            out.append(arr)
    return jax.tree_util.tree_unflatten(
        treedef, [jax.numpy.asarray(a) for a in out])
