"""Dense-parameter checkpointing — pytree ↔ npz.

The reference persists dense params by copying the thread-0 scope back to the
root scope at trainer Finalize (boxps_trainer.cc:123-131) and then calling
``fluid.io.save_persistables``. Here the dense state is a JAX pytree
(params + optimizer state); we serialize it keyed by tree path so load is
order-independent and shape-checked.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def save_pytree(tree: Any, fname: str, compress: bool = True) -> str:
    """compress=False writes STORED zip members (plain .npy bytes at a
    fixed offset) so non-Python clients can mmap the arrays directly —
    the serving export uses this (native/serving_score.c)."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    # force C order: XLA may hand back an F-contiguous view of its chosen
    # device layout, and np.save would then write fortran_order=True —
    # which the mmap-based C serving client (serving_score.c) rejects.
    # (order="C", not ascontiguousarray: the latter promotes 0-d leaves
    # like adam's count to (1,), breaking load_pytree's shape check)
    arrays = {_path_str(path): np.asarray(leaf, order="C")
              for path, leaf in leaves}
    os.makedirs(os.path.dirname(fname) or ".", exist_ok=True)
    (np.savez_compressed if compress else np.savez)(fname, **arrays)
    return fname


def load_pytree(template: Any, fname: str) -> Any:
    """Load into the structure of `template` (shapes must match)."""
    z = np.load(fname)
    leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    out = []
    for path, leaf in leaves:
        key = _path_str(path)
        if key not in z:
            raise KeyError(f"checkpoint {fname} missing leaf {key!r}")
        arr = z[key]
        want = np.shape(leaf)
        if tuple(arr.shape) != tuple(want):
            raise ValueError(
                f"leaf {key!r}: checkpoint shape {arr.shape} != {want}")
        out.append(arr)
    return jax.tree_util.tree_unflatten(
        treedef, [jax.numpy.asarray(a) for a in out])
