"""Per-stage wall-clock timers — back-compat shim.

``StageTimers`` moved to :mod:`paddlebox_tpu.monitor.timers` (the telemetry
hub owns the per-stage instrument: totals feed per-pass flight records and
each scope emits a tagged span event when the hub's stream is on). This
module keeps the historical import path working.
"""

from __future__ import annotations

from paddlebox_tpu.monitor.timers import StageTimers  # noqa: F401
