"""Per-stage wall-clock timers.

The reference instruments every per-card stage: read/trans/cal/sync/main
times printed by ``log_for_profile`` (boxps_worker.cc:746-759) plus the
pull/push/dense-sync timers in DeviceBoxData (box_wrapper.h:375-391,
PrintSyncTimer h:642). ``StageTimers`` is the equivalent instrument; the
bench harness and trainer use it so throughput numbers stay comparable
(BASELINE.md "In-repo measurement hooks").
"""

from __future__ import annotations

import contextlib
import time


class StageTimers:
    def __init__(self, stages: list[str]):
        self.total: dict[str, float] = {s: 0.0 for s in stages}
        self.count: dict[str, int] = {s: 0 for s in stages}

    @contextlib.contextmanager
    def __call__(self, stage: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.total[stage] = self.total.get(stage, 0.0) + dt
            self.count[stage] = self.count.get(stage, 0) + 1

    def mean(self, stage: str) -> float:
        c = self.count.get(stage, 0)
        return self.total.get(stage, 0.0) / c if c else 0.0

    def report(self) -> str:
        """One log_for_profile-style line."""
        parts = [f"{s}={self.total[s]:.3f}s/{self.count[s]}"
                 for s in self.total]
        return " ".join(parts)

    def reset(self) -> None:
        for s in self.total:
            self.total[s] = 0.0
            self.count[s] = 0
