from paddlebox_tpu.utils.profiler import (RecordEvent, STATS,  # noqa: F401
                                          DumpStream, StatRegistry,
                                          disable_profiler, dump_tree,
                                          enable_profiler,
                                          export_chrome_trace,
                                          find_nonfinite, stat_add, stat_get)
from paddlebox_tpu.utils.timer import StageTimers  # noqa: F401
from paddlebox_tpu.utils.checkpoint import (  # noqa: F401
    CheckpointCorruptError)
from paddlebox_tpu.utils.pass_ckpt import PassCheckpointer  # noqa: F401
