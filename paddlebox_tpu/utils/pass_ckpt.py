"""Unified crash-safe pass snapshots + resume — the PassCheckpointer.

The reference's production loop survives preemption at pass granularity:
``SaveBase`` writes the day's batch model, ``end_pass(need_save_delta)``
emits per-pass deltas, and a restarted worker loads the newest base +
replays the delta donefiles (SURVEY.md §5; fleet_util.py:649-745). Our
reproduction adds what the open-source glue leaves implicit: *atomic*
snapshots with verified manifests, and a resume that restores every plane
a pass touches —

- dense params + optimizer state (utils/checkpoint.save_pytree, mode-aware
  through ``Trainer.restore_dense`` — allreduce/kstep/async),
- the sparse table as a base-or-delta chain (``store.save_base`` /
  ``save_delta``; a fresh base every ``base_every`` passes bounds replay
  length and lets retention reclaim old chains),
- metric/AUC registry state and the join/update phase bit,
- the pass/step cursor (``BoxPS.pass_id``, ``date``,
  ``Trainer.global_step``) — plus, since ISSUE 5, the **dataset/shuffle
  cursor**: ``mid_steps`` (steps already trained inside an open pass) and
  the shuffle RNG state (``SlotDataset.shuffle_state``), so a kill
  mid-pass resumes deterministically from the cursor instead of replaying
  the pass,

after first flushing the device tier (pending deferred push applies +
lazily-retained rows — ``Trainer.flush_sparse``), so the snapshot is the
complete post-pass state.

Commit protocol: every member lands atomically (tmp → fsync → replace);
the snapshot's ``MANIFEST.json`` — carrying the cursor, the chain
reference with per-member CRC32s, and checksums of the snapshot's own
files — is written LAST. A snapshot without a committed manifest never
happened; one whose checksums no longer verify is diagnosed and skipped.
``resume`` therefore walks snapshots newest-first and restores the first
one that fully verifies, falling back past a torn/truncated newest
snapshot automatically — or restores exactly the cursor a multi-host
resume ELECTION agreed on (``resume(at=...)``,
distributed/resilience.coordinated_resume), discarding any newer local
snapshots from the abandoned timeline. ``keep_last_n`` prunes old
snapshots (and any sparse chain directory no surviving snapshot
references) after each successful save.

Remote (``hdfs://``/``afs://``/…) roots: construct with a remote URI and
the checkpointer stages locally — the full atomic local commit runs
first, then the snapshot dir + new chain members upload over the
registered CommandFS (riding its retry/backoff), and a line lands in
``snapshots.donefile`` only AFTER the upload, so a torn upload is never
discoverable. Resume with an empty local staging dir reads the donefile
newest-first, downloads to a temp dir, verifies, and falls back past any
entry that fails to download or verify (with a diagnostic event).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
import time
import warnings
from typing import Any

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags as config_flags
from paddlebox_tpu.utils import checkpoint as ckpt_lib
from paddlebox_tpu.utils import faultpoint
from paddlebox_tpu.utils import fs as fs_lib
from paddlebox_tpu.utils import profiler
from paddlebox_tpu.utils.checkpoint import CheckpointCorruptError

_PASS_RE = re.compile(r"^pass-(\d+)(?:\.mid(\d+))?$")
_CHAIN_RE = re.compile(r"^chain-(\d+)$")

REMOTE_DONEFILE = "snapshots.donefile"


def _dense_tree(trainer) -> dict[str, Any]:
    return {"params": trainer.params, "opt_state": trainer.opt_state}


def _metric_tree(metrics) -> dict[str, Any]:
    return {name: metrics.get_state(name) for name in metrics.names()}


class PassCheckpointer:
    """Owns one snapshot root. One instance per training job; the driver
    calls :meth:`save` at every pass boundary (directly or through
    ``BoxPS.end_pass``) and :meth:`resume` once at startup.

    ``root`` may be a remote URI (any scheme registered with utils/fs.py);
    snapshots then stage under ``staging_dir`` (a fresh temp dir by
    default — the remote root is authoritative across host loss) and
    mirror up after each local commit."""

    def __init__(self, root: str, keep_last_n: int | None = None,
                 base_every: int | None = None,
                 staging_dir: str | None = None):
        if fs_lib.is_remote(root):
            self.remote_root: str | None = root.rstrip("/")
            self.root = staging_dir or tempfile.mkdtemp(
                prefix="pbtpu_ckpt_stage_")
        else:
            self.remote_root = None
            self.root = root
        self.keep_last_n = (config_flags.ckpt_keep_last_n
                            if keep_last_n is None else int(keep_last_n))
        if self.keep_last_n < 2:
            # fallback-past-a-torn-newest needs at least one predecessor
            raise ValueError("keep_last_n must be >= 2 for crash safety")
        self.base_every = (config_flags.ckpt_base_every
                           if base_every is None else int(base_every))
        os.makedirs(self.root, exist_ok=True)
        self._chain_gen = 0
        self._chain_dir: str | None = None
        self._deltas_in_chain = 0
        # chains whose FULL directory this process already mirrored up; a
        # chain continued across a restart re-uploads whole once, then
        # rides the incremental per-delta path
        self._uploaded_chains: set[str] = set()
        self._remote_synced = False
        # store.save_count as of OUR last save/resume: any foreign
        # save_base/save_delta in between (e.g. FleetUtil donefile models
        # sharing the store) consumed the dirty mask + tombstones, so the
        # next snapshot must be a full base — a delta into our chain
        # would silently miss the rows/evictions the foreign save carried
        # away. The MONOTONIC count is the guard (save_seq can't be: a
        # foreign save_base resets it to 0, aliasing "nothing happened")
        self._expect_count: int | None = None

    # ---- paths -----------------------------------------------------------

    def snap_name(self, pass_id: int, mid_steps: int = 0) -> str:
        """``pass-PPPPP`` for a pass-boundary snapshot; a mid-pass one is
        ``pass-PPPPP.midSSSSS`` — pass_id is the last COMPLETED pass and
        mid_steps the steps already trained into the next. Lexicographic
        name order == (pass_id, mid_steps) cursor order."""
        name = f"pass-{pass_id:05d}"
        if mid_steps:
            name += f".mid{mid_steps:05d}"
        return name

    def snap_dir(self, pass_id: int, mid_steps: int = 0) -> str:
        return os.path.join(self.root, self.snap_name(pass_id, mid_steps))

    def _chain_path(self, name: str) -> str:
        return os.path.join(self.root, name)

    def _list_snaps(self) -> list[tuple[int, int, str]]:
        """[(pass_id, mid_steps, path)] sorted ascending by cursor."""
        out = []
        for n in os.listdir(self.root):
            m = _PASS_RE.match(n)
            if m and os.path.isdir(os.path.join(self.root, n)):
                out.append((int(m.group(1)), int(m.group(2) or 0),
                            os.path.join(self.root, n)))
        return sorted(out)

    # ---- save ------------------------------------------------------------

    def save(self, trainer, box=None, metrics=None,
             pass_id: int | None = None, mid_steps: int = 0,
             dense_override: tuple | None = None,
             shuffle_state: dict | None = None) -> str:
        """Snapshot the complete post-pass state. Returns the snapshot dir.

        Members land atomically in dependency order (sparse chain → dense
        → metrics), manifest last — a kill anywhere before the manifest
        commit leaves this snapshot invisible and the previous one intact.

        ``mid_steps`` > 0 marks a MID-pass snapshot: ``pass_id`` is then
        the last completed pass and the cursor records how many steps of
        the open pass are already trained (the trainer's midpass hook
        passes the live dense planes via ``dense_override`` — mid-pass,
        ``trainer.params`` still holds the pass-start values).
        ``shuffle_state`` rides the cursor so the resumed rank replays the
        identical pass order (SlotDataset.shuffle_state).
        """
        t_save0 = time.perf_counter()
        if pass_id is None:
            if box is None:
                raise ValueError("save needs pass_id or a BoxPS")
            pass_id = int(box.pass_id)
        metrics = metrics if metrics is not None else (
            box.metrics if box is not None else None)
        # device tier → host store: pending deferred push apply lands,
        # then unsynced resident rows move D2H (the stager/feed flush the
        # snapshot's completeness depends on)
        trainer.flush_sparse()

        # sparse plane: rotate to a fresh base chain on the first save,
        # every base_every-th pass after, and whenever another writer
        # saved the store since our last snapshot (its delta consumed the
        # dirty rows ours would need — only a full base is still exact)
        rotate = (self._chain_dir is None
                  or (self.base_every > 0
                      and self._deltas_in_chain >= self.base_every - 1)
                  or trainer.store.save_count != self._expect_count)
        # chain bookkeeping commits only AFTER the store save succeeds: a
        # transient failure (ENOSPC, injected IO error) must leave the
        # checkpointer pointing at the last good chain state, not at a
        # half-open baseless chain every later save would trip over
        if rotate:
            gen = self._chain_gen + 1
            chain_name = f"chain-{gen:04d}"
            trainer.store.save_base(self._chain_path(chain_name),
                                    pass_id=pass_id)
            self._chain_gen = gen
            self._chain_dir = chain_name
            self._deltas_in_chain = 0
        else:
            chain_name = self._chain_dir
            trainer.store.save_delta(self._chain_path(chain_name),
                                     pass_id=pass_id)
            self._deltas_in_chain += 1
        save_seq = trainer.store.save_seq
        self._expect_count = trainer.store.save_count
        # the store knows its own chain layout (flat base+deltas, or a
        # ShardedEmbeddingStore's shard-prefixed members) — the snapshot
        # records exactly those entries' CRCs and resume verifies them
        chain_files = trainer.store.chain_file_entries(
            self._chain_path(chain_name), save_seq)
        # what a delta save touched — the incremental remote upload set
        # (per-shard delta + manifests for sharded stores)
        incr_members = (None if rotate
                        else trainer.store.chain_increment_members(save_seq))

        snap = self.snap_dir(pass_id, mid_steps)
        os.makedirs(snap, exist_ok=True)
        files: dict[str, dict] = {}
        dense_f = os.path.join(snap, "dense.npz")
        if dense_override is not None:
            dense_tree = {"params": dense_override[0],
                          "opt_state": dense_override[1]}
        else:
            dense_tree = _dense_tree(trainer)
        ckpt_lib.save_pytree(dense_tree, dense_f)
        files["dense.npz"] = ckpt_lib.file_entry(dense_f)
        if metrics is not None and metrics.names():
            met_f = os.path.join(snap, "metrics.npz")
            ckpt_lib.save_pytree(_metric_tree(metrics), met_f)
            files["metrics.npz"] = ckpt_lib.file_entry(met_f)

        cursor = {
            "pass_id": int(pass_id),
            "global_step": int(trainer.global_step),
            "date": None if box is None else box.date,
            "phase": None if metrics is None else int(metrics.phase),
            "mid_steps": int(mid_steps),
            "shuffle_state": shuffle_state,
        }
        if mid_steps:
            parent = self.snap_name(pass_id)          # the completed pass
        else:
            parent = (self.snap_name(pass_id - 1) if pass_id > 1 else None)
        faultpoint.hit("pass_ckpt.pre_manifest")
        ckpt_lib.write_manifest(
            snap, files, cursor=cursor, save_seq=save_seq,
            chain_dir=chain_name, chain_files=chain_files,
            parent_snapshot=parent)
        faultpoint.hit("pass_ckpt.post_manifest")
        # checkpoint lifecycle telemetry: duration + bytes per save, plus
        # a chrome-trace instant so the timeline reads commit points
        seconds = time.perf_counter() - t_save0
        sparse_member = ("base.npz" if rotate
                         else f"delta-{save_seq:05d}.npz")
        nbytes = (sum(e["bytes"] for e in files.values())
                  + sum(e["bytes"] for name, e in chain_files.items()
                        if name.endswith(sparse_member)))
        monitor.counter_add("ckpt.saves")
        monitor.counter_add("ckpt.save_seconds", seconds)
        monitor.counter_add("ckpt.bytes", nbytes)
        if rotate:
            monitor.counter_add("ckpt.base_rotations")
        if mid_steps:
            monitor.counter_add("ckpt.midpass_saves")
        monitor.event("checkpoint_save", type="lifecycle",
                      snapshot=os.path.basename(snap), seconds=seconds,
                      bytes=int(nbytes), rotated=bool(rotate),
                      chain=chain_name, save_seq=int(save_seq),
                      mid_steps=int(mid_steps))
        profiler.record_instant("checkpoint_commit",
                                {"snapshot": os.path.basename(snap)})
        if self.remote_root is not None:
            self._upload(snap, chain_name, rotate, save_seq, cursor,
                         incr_members=incr_members)
        self._prune()
        return snap

    # ---- remote mirror ---------------------------------------------------

    def _remote_fs(self):
        fs, _ = fs_lib.resolve(self.remote_root)
        return fs

    def _upload(self, snap: str, chain_name: str, rotated: bool,
                save_seq: int, cursor: dict,
                incr_members: list[str] | None = None) -> None:
        """Mirror the just-committed snapshot to the remote root. Donefile
        line lands ONLY after every byte uploaded — a kill anywhere in
        here leaves the remote donefile naming only complete uploads (the
        local commit already happened, so a same-host restart loses
        nothing either)."""
        t0 = time.perf_counter()
        faultpoint.hit("remote_ckpt.upload.pre")
        fs = self._remote_fs()
        rroot = self.remote_root
        snap_name = os.path.basename(snap)
        local_chain = self._chain_path(chain_name)
        remote_chain = f"{rroot}/{chain_name}"
        try:
            fs.makedirs(rroot)
            if (rotated or incr_members is None
                    or chain_name not in self._uploaded_chains):
                # whole-chain upload: fresh rotation, or a chain
                # continued across a process restart (unknown remote
                # contents — replace)
                fs_lib.put_replacing(fs, local_chain, remote_chain)
            else:
                # incremental: only what the delta save touched crosses
                # the wire — the store's chain_increment_members (per-
                # shard delta + manifests for sharded stores, whose
                # subdirs the rotation's whole-chain upload created;
                # makedirs is the idempotent belt-and-braces)
                for d in sorted({os.path.dirname(m) for m in incr_members
                                 if "/" in m}):
                    fs.makedirs(f"{remote_chain}/{d}")
                for name in incr_members:
                    fs.put(os.path.join(local_chain, name),
                           f"{remote_chain}/{name}")
            self._uploaded_chains.add(chain_name)
            # a leftover target (torn upload / re-save after an elected
            # rollback) must never nest the source (fs_lib.put_replacing)
            fs_lib.put_replacing(fs, snap, f"{rroot}/{snap_name}")
        except BaseException:
            # a half-uploaded chain must not ride the incremental path on
            # the next save — force a full re-upload (download-side CRC
            # verification is the backstop, this is the repair)
            self._uploaded_chains.discard(chain_name)
            raise
        line = json.dumps({"pass": int(cursor["pass_id"]),
                           "mid": int(cursor["mid_steps"]),
                           "snapshot": snap_name, "chain": chain_name,
                           "save_seq": int(save_seq),
                           "ts": int(time.time())})
        self._repair_donefile(fs)
        # pblint: disable=donefile-discipline -- snapshots.donefile is the
        # checkpoint mirror's OWN resume channel (PR 5), not the model-
        # visibility donefile: it needs reset-line masking and two-phase
        # compaction, rewrite semantics FleetUtil.append_donefile cannot
        # express (and must not learn)
        fs.write_text(f"{rroot}/{REMOTE_DONEFILE}", line + "\n",
                      append=True)
        seconds = time.perf_counter() - t0
        monitor.counter_add("ckpt.remote_uploads")
        monitor.counter_add("ckpt.remote_upload_seconds", seconds)
        monitor.event("checkpoint_remote_upload", type="lifecycle",
                      snapshot=snap_name, chain=chain_name,
                      seconds=seconds)

    def _read_donefile_raw(self) -> list[str]:
        """Raw donefile lines. Falls back to the ``.compact`` staging
        copy when the main file is missing — the compaction rewrite
        uploads the compacted content there FIRST, so a kill between the
        main file's rm and put can never lose the donefile."""
        fs = self._remote_fs()
        path = f"{self.remote_root}/{REMOTE_DONEFILE}"
        if not fs.exists(path):
            alt = f"{path}.compact"
            if not fs.exists(alt):
                return []
            path = alt
        return [ln.strip() for ln in fs.read_lines(path) if ln.strip()]

    def _repair_donefile(self, fs) -> None:
        """Finish an interrupted compaction BEFORE appending: a kill
        between the compaction's rm and put leaves only the ``.compact``
        staging copy — readers fall back to it, but an append would
        recreate the main file with a single line, silently shadowing
        the whole history (and the next prune would then reclaim every
        'unreferenced' dir). Restore the main file from the staging copy
        first; the append then extends the full history."""
        path = f"{self.remote_root}/{REMOTE_DONEFILE}"
        alt = f"{path}.compact"
        if fs.exists(path) or not fs.exists(alt):
            return
        tmp = os.path.join(self.root, f".donefile.repair.{os.getpid()}")
        try:
            fs.get(alt, tmp)
            # pblint: disable=donefile-discipline -- compaction-crash
            # repair of the mirror's OWN snapshots.donefile: restores the
            # full history from the .compact staging copy; append-only
            # FleetUtil semantics cannot repair a half-replaced file
            fs.put(tmp, path)
            fs.rm(alt)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        monitor.counter_add("ckpt.donefile_repairs")
        monitor.event("donefile_repaired", type="lifecycle")

    def _remote_entries(self, raw_lines: list[str] | None = None
                        ) -> list[dict]:
        """Donefile entries in append order, with ``reset_after`` lines
        applied: an elected rollback masks the abandoned timeline's newer
        entries so a later restore can never resurrect them."""
        out: list[dict] = []
        for raw in (self._read_donefile_raw() if raw_lines is None
                    else raw_lines):
            e = json.loads(raw)
            if "reset_after" in e:
                ra = tuple(e["reset_after"])
                out = [x for x in out
                       if (int(x["pass"]), int(x.get("mid", 0))) <= ra]
            else:
                out.append(e)
        return out

    def _sync_from_remote(self) -> bool:
        """Populate the local staging root from the remote donefile:
        download up to ``keep_last_n`` entries (newest first — chains
        shared between entries cross the wire once) to a temp dir, land
        them locally, verify each; fall back past entries that fail to
        download or verify, with a diagnostic. Returns True when at least
        one verified snapshot landed.

        Multiple entries matter for the multi-host election: a
        replacement host that synced only the newest cursor would publish
        a single candidate, and any surviving rank missing exactly that
        cursor would collapse the intersection — and the whole world —
        to a fresh start even though an older COMMON cursor sits one
        donefile entry back."""
        self._remote_synced = True
        try:
            entries = self._remote_entries()
        except (RuntimeError, ValueError, OSError) as e:
            warnings.warn(f"remote snapshot donefile unreadable ({e}); "
                          f"starting fresh")
            return False
        fs = self._remote_fs()
        landed = 0
        got_chains: set[str] = set()
        for e in reversed(entries):
            if landed >= self.keep_last_n:
                break
            snap_name, chain_name = e["snapshot"], e["chain"]
            try:
                faultpoint.hit("remote_ckpt.download.pre")
                names = [chain_name] if chain_name not in got_chains \
                    else []
                names.append(snap_name)
                with tempfile.TemporaryDirectory(dir=self.root) as tmp:
                    for name in names:
                        fs.get(f"{self.remote_root}/{name}",
                               os.path.join(tmp, name))
                    for name in names:
                        dst = os.path.join(self.root, name)
                        shutil.rmtree(dst, ignore_errors=True)
                        os.replace(os.path.join(tmp, name), dst)
                self._verify_snapshot(os.path.join(self.root, snap_name))
            except (RuntimeError, OSError, CheckpointCorruptError) as err:
                monitor.counter_add("ckpt.remote_fallbacks")
                monitor.event("checkpoint_remote_fallback",
                              type="lifecycle", snapshot=snap_name,
                              error=str(err)[:300])
                warnings.warn(
                    f"remote snapshot {snap_name} failed to restore "
                    f"({err}); falling back to the previous donefile "
                    f"entry")
                continue
            got_chains.add(chain_name)
            landed += 1
            monitor.counter_add("ckpt.remote_downloads")
            monitor.event("checkpoint_remote_download", type="lifecycle",
                          snapshot=snap_name, chain=chain_name)
        return landed > 0

    # ---- discovery / verification ---------------------------------------

    def _verify_snapshot(self, snap: str) -> dict:
        """Full snapshot verification: manifest present, snapshot members
        checksum clean, and the sparse chain prefix it references intact
        — against the CRCs the snapshot itself recorded (the chain dir's
        live manifest may already describe a newer save)."""
        manifest = ckpt_lib.verify_manifest(snap)
        try:
            int(manifest["cursor"]["pass_id"])     # resume depends on it
            int(manifest["cursor"]["global_step"])
            chain_dir = self._chain_path(manifest["chain_dir"])
            if any("/" in n for n in manifest.get("chain_files", {})):
                # store-defined layout (a sharded store's shard-prefixed
                # members): verify exactly what the snapshot recorded
                need = sorted(manifest["chain_files"])
            else:
                need = (["base.npz"]
                        + [f"delta-{i:05d}.npz"
                           for i in range(1,
                                          int(manifest["save_seq"]) + 1)])
        except (KeyError, TypeError, ValueError) as e:
            raise CheckpointCorruptError(
                os.path.join(snap, ckpt_lib.MANIFEST_NAME),
                f"snapshot manifest missing/invalid field ({e!r})")
        chain_files = manifest.get("chain_files", {})
        try:
            # same missing/size/crc checks as any manifest, but against
            # the CRCs the SNAPSHOT recorded
            ckpt_lib.verify_manifest(chain_dir, {"files": chain_files},
                                     only=need)
        except CheckpointCorruptError as e:
            # position by chain-relative name: shard-prefixed members
            # ('shard-NN/delta-…') would never match a bare basename
            rel = os.path.relpath(e.fname, chain_dir).replace(os.sep, "/")
            pos = need.index(rel) if rel in need else -1
            raise CheckpointCorruptError(
                e.fname,
                f"chain member #{pos} of the {len(need)} recorded in "
                f"snapshot {os.path.basename(snap)}: {e}") from e
        return manifest

    def intact_cursors(self) -> list[tuple[int, int]]:
        """Every locally intact snapshot's ``(pass_id, mid_steps)``,
        ascending — the candidate list this rank publishes into the
        multi-host resume election. An empty local staging dir with a
        remote root syncs the newest remote entry down first, so a
        replacement host joins the election with what the donefile can
        actually deliver."""
        out = []
        for pass_id, mid, snap in self._list_snaps():
            try:
                self._verify_snapshot(snap)
                out.append((pass_id, mid))
            except CheckpointCorruptError:
                continue
        if not out and self.remote_root is not None \
                and not self._remote_synced:
            if self._sync_from_remote():
                return self.intact_cursors()
        return out

    def latest_valid(self) -> tuple[int, str, dict] | None:
        """Newest snapshot that fully verifies, walking past torn ones
        (with a warning naming the diagnosis). None = nothing to resume.
        Returns (pass_id, snap_dir, manifest) — a mid-pass snapshot's
        mid_steps rides manifest["cursor"]."""
        for _attempt in (0, 1):
            for pass_id, mid, snap in reversed(self._list_snaps()):
                try:
                    return pass_id, snap, self._verify_snapshot(snap)
                except CheckpointCorruptError as e:
                    # flaky-storage observability: a torn snapshot shows
                    # up in the flight record / exposition, not only in
                    # this warning
                    monitor.counter_add("ckpt.torn_fallbacks")
                    monitor.event("checkpoint_torn_fallback",
                                  type="lifecycle",
                                  snapshot=os.path.basename(snap),
                                  error=str(e)[:300])
                    warnings.warn(
                        f"snapshot {snap} failed verification ({e}); "
                        f"falling back to the previous one")
            # nothing locally intact (none, or all torn): a remote root
            # may still deliver — sync once and re-walk
            if _attempt == 0 and self.remote_root is not None \
                    and not self._remote_synced:
                if not self._sync_from_remote():
                    break
            else:
                break
        return None

    # ---- resume ----------------------------------------------------------

    def resume(self, trainer, box=None, metrics=None,
               at: tuple[int, int] | None = None) -> dict | None:
        """Restore every plane from the newest valid snapshot; return its
        cursor dict ({pass_id, global_step, date, phase, mid_steps,
        shuffle_state}), or None when no valid snapshot exists (fresh
        start). The driver re-enters its pass loop at
        ``cursor['pass_id'] + 1`` (skipping the first ``mid_steps`` steps
        of that pass when resuming mid-pass).

        ``at=(pass_id, mid_steps)`` restores EXACTLY that snapshot — the
        multi-host election's contract: every rank lands on the agreed
        cursor, and any newer local snapshots (an abandoned timeline the
        world did not elect) are discarded so they can never resurface.
        Raises if the elected snapshot is missing or torn (the rank
        claimed it intact in the election)."""
        t_res0 = time.perf_counter()
        if at is not None:
            at = (int(at[0]), int(at[1]))
            snap = self.snap_dir(*at)
            try:
                manifest = self._verify_snapshot(snap)
            except CheckpointCorruptError as e:
                raise RuntimeError(
                    f"elected snapshot {self.snap_name(*at)} no longer "
                    f"verifies on this rank: {e}") from e
            pass_id = at[0]
        else:
            found = self.latest_valid()
            if found is None:
                return None
            pass_id, snap, manifest = found
        cursor = dict(manifest["cursor"])
        cursor.setdefault("mid_steps", 0)
        cursor.setdefault("shuffle_state", None)
        chain_name = manifest["chain_dir"]
        seq = int(manifest["save_seq"])

        # sparse plane, in place: mutation_count bump invalidates any
        # device-resident rows the feed manager still holds. Chain already
        # verified against the snapshot's own CRCs above.
        trainer.store.restore(self._chain_path(chain_name),
                              upto_seq=seq, verify=False)

        # dense plane (mode-aware: allreduce/kstep/async via restore_dense)
        dense = ckpt_lib.load_pytree(
            _dense_tree(trainer), os.path.join(snap, "dense.npz"))
        trainer.restore_dense(dense["params"], dense["opt_state"])
        trainer.global_step = int(cursor["global_step"])

        metrics = metrics if metrics is not None else (
            box.metrics if box is not None else None)
        if metrics is not None and "metrics.npz" in manifest["files"]:
            states = ckpt_lib.load_pytree(
                _metric_tree(metrics), os.path.join(snap, "metrics.npz"))
            for name, state in states.items():
                metrics.set_state(name, state)
            if cursor.get("phase") is not None:
                metrics.phase = int(cursor["phase"])
        if box is not None:
            box.pass_id = int(cursor["pass_id"])
            box.in_pass = False
            if cursor.get("date") is not None:
                box.date = int(cursor["date"])

        if at is not None:
            self._discard_newer_than(at)

        # continue the chain where the snapshot left it: the next save
        # deltas into the same chain dir (store._save_seq was set by
        # restore; stale higher-numbered deltas from the crashed run get
        # overwritten as the re-run reaches them)
        self._chain_dir = chain_name
        self._chain_gen = max(self._chain_gen,
                              int(_CHAIN_RE.match(chain_name).group(1)))
        self._deltas_in_chain = seq
        # store.restore replayed the chain and left save_seq at `seq`; a
        # foreign save between now and our next snapshot bumps save_count
        # and forces the rotation
        self._expect_count = trainer.store.save_count
        seconds = time.perf_counter() - t_res0
        monitor.counter_add("ckpt.resumes")
        monitor.counter_add("ckpt.resume_seconds", seconds)
        monitor.event("checkpoint_resume", type="lifecycle",
                      snapshot=os.path.basename(snap), seconds=seconds,
                      resumed_pass=int(cursor["pass_id"]),
                      mid_steps=int(cursor["mid_steps"]),
                      chain=chain_name, save_seq=seq, elected=at is not None)
        return cursor

    def discard_all_snapshots(self) -> None:
        """Remove every local snapshot (and mask all remote donefile
        entries with a reset line). The fresh-start arm of the multi-host
        election: a world whose intersection is empty retrains from pass
        1, and a stale pass-N snapshot surviving on one rank could alias
        a freshly-retrained pass-N on another at the NEXT election —
        silent divergence. (-1, 0) sorts below every real cursor."""
        self._discard_newer_than((-1, 0))

    def _discard_newer_than(self, at: tuple[int, int]) -> None:
        """Remove local snapshots newer than the elected cursor — they
        belong to a timeline the world abandoned and must never win a
        later newest-first walk — and mask them in the remote donefile
        with a ``reset_after`` line (their dirs get overwritten as the
        re-run reaches those passes again)."""
        dropped = [(p, m, s) for p, m, s in self._list_snaps()
                   if (p, m) > at]
        for p, m, s in dropped:
            shutil.rmtree(s, ignore_errors=True)
        if dropped:
            monitor.event("checkpoint_timeline_reset", type="lifecycle",
                          elected=list(at),
                          dropped=[os.path.basename(s)
                                   for _, _, s in dropped])
        if self.remote_root is not None:
            try:
                fs = self._remote_fs()
                if fs.exists(f"{self.remote_root}/{REMOTE_DONEFILE}"):
                    line = json.dumps({"reset_after": list(at),
                                       "ts": int(time.time())})
                    # pblint: disable=donefile-discipline -- timeline-
                    # reset mask on the mirror's OWN snapshots.donefile
                    # (PR 5 election rollback); reset_after lines are a
                    # resume-channel concept FleetUtil does not speak
                    fs.write_text(
                        f"{self.remote_root}/{REMOTE_DONEFILE}",
                        line + "\n", append=True)
            except RuntimeError as e:
                # the election already agreed; a masked donefile is an
                # optimization of later restores, not a correctness gate
                warnings.warn(f"remote donefile reset failed ({e})")

    # ---- retention -------------------------------------------------------

    def _prune(self) -> None:
        """Drop snapshots beyond keep_last_n, then chain dirs no surviving
        snapshot references. Never touches the open chain.

        Pass-boundary and mid-pass snapshots retain in SEPARATE pools
        (keep_last_n each): ranks mid-pass-snapshot on their own step
        cadence, so letting a fast rank's mids evict its pass-boundary
        snapshots would strip the cursors the ranks still hold in COMMON
        and collapse the next election to a fresh start."""
        snaps = self._list_snaps()
        fulls = [s for s in snaps if s[1] == 0]
        mids = [s for s in snaps if s[1] > 0]
        for _, _, snap in (fulls[:-self.keep_last_n]
                           + mids[:-self.keep_last_n]):
            shutil.rmtree(snap, ignore_errors=True)
        referenced = {self._chain_dir}
        for _, _, snap in self._list_snaps():
            try:
                m = ckpt_lib.read_manifest(snap)
            except CheckpointCorruptError:
                continue     # unusable snapshot; resume skips it too
            if m is not None:
                referenced.add(m.get("chain_dir"))
        for n in os.listdir(self.root):
            if _CHAIN_RE.match(n) and n not in referenced:
                shutil.rmtree(os.path.join(self.root, n),
                              ignore_errors=True)
        if self.remote_root is not None:
            try:
                self._prune_remote()
            except (RuntimeError, OSError, ValueError) as e:
                # retention is hygiene, not correctness: the donefile's
                # download-side verification is the backstop, and the
                # next save retries the compaction
                warnings.warn(f"remote snapshot retention failed ({e}); "
                              f"will retry at the next save")

    def _prune_remote(self) -> None:
        """Mirror-side retention (ISSUE 6 satellite): without this, the
        remote root and ``snapshots.donefile`` grow unboundedly — every
        pass appends a line and uploads a dir, and an elected rollback's
        masked (``reset_after``-shadowed) entries stay on disk forever.

        Keeps the newest ``keep_last_n`` donefile entries per pool
        (pass-boundary and mid-pass separately, mirroring local
        retention), rewrites the donefile to exactly those lines —
        dropping pruned AND masked lines — and then removes remote
        snapshot/chain dirs no kept entry references. Order matters for
        crash safety: the donefile shrinks FIRST (a kill after that
        leaves orphan dirs the next compaction reclaims, never a donefile
        line naming a deleted dir), and the rewrite itself stages the
        compacted content at ``snapshots.donefile.compact`` before
        replacing the main file (readers fall back to the staging copy,
        so no kill point loses the donefile)."""
        raw = self._read_donefile_raw()
        if not raw:
            return
        entries = self._remote_entries(raw)
        keep = max(1, int(self.keep_last_n))
        fulls = [e for e in entries if not int(e.get("mid", 0))]
        mids = [e for e in entries if int(e.get("mid", 0))]
        kept_ids = {id(e) for e in fulls[-keep:] + mids[-keep:]}
        kept = [e for e in entries if id(e) in kept_ids]
        fs = self._remote_fs()
        donefile = f"{self.remote_root}/{REMOTE_DONEFILE}"
        if len(kept) != len(raw):
            # two-phase donefile rewrite: stage → replace → unstage
            tmp = os.path.join(self.root,
                               f".donefile.compact.{os.getpid()}")
            # pblint: disable=durable-write,donefile-discipline -- local
            # STAGING copy of the compacted mirror donefile: durability
            # comes from the two-phase remote protocol below (stage ->
            # replace -> unstage), not from this scratch file
            with open(tmp, "w") as f:
                for e in kept:
                    f.write(json.dumps(e) + "\n")
            try:
                fs.rm(f"{donefile}.compact")
                # pblint: disable=donefile-discipline -- two-phase
                # compaction STAGE upload (readers fall back to .compact
                # if the replace below is interrupted)
                fs.put(tmp, f"{donefile}.compact")
                fs.rm(donefile)
                # pblint: disable=donefile-discipline -- two-phase
                # compaction REPLACE of the mirror's own snapshots.
                # donefile; a rewrite-in-place is exactly what the
                # append-only FleetUtil API exists to forbid elsewhere
                fs.put(tmp, donefile)
                fs.rm(f"{donefile}.compact")
            finally:
                os.remove(tmp)
            monitor.counter_add("ckpt.donefile_compactions")
            monitor.event("donefile_compacted", type="lifecycle",
                          dropped=len(raw) - len(kept), kept=len(kept))
        kept_snaps = {e["snapshot"] for e in kept}
        kept_chains = {e["chain"] for e in kept}
        if self._chain_dir is not None:
            kept_chains.add(self._chain_dir)     # the open chain
        removed = 0
        for path in fs.ls(self.remote_root):
            name = os.path.basename(path.rstrip("/"))
            if _PASS_RE.match(name) and name not in kept_snaps:
                fs.rm(f"{self.remote_root}/{name}")
                removed += 1
            elif _CHAIN_RE.match(name) and name not in kept_chains:
                fs.rm(f"{self.remote_root}/{name}")
                self._uploaded_chains.discard(name)
                removed += 1
        if removed:
            monitor.counter_add("ckpt.remote_pruned_dirs", removed)
