"""Unified crash-safe pass snapshots + resume — the PassCheckpointer.

The reference's production loop survives preemption at pass granularity:
``SaveBase`` writes the day's batch model, ``end_pass(need_save_delta)``
emits per-pass deltas, and a restarted worker loads the newest base +
replays the delta donefiles (SURVEY.md §5; fleet_util.py:649-745). Our
reproduction adds what the open-source glue leaves implicit: *atomic*
snapshots with verified manifests, and a resume that restores every plane
a pass touches —

- dense params + optimizer state (utils/checkpoint.save_pytree, mode-aware
  through ``Trainer.restore_dense`` — allreduce/kstep/async),
- the sparse table as a base-or-delta chain (``store.save_base`` /
  ``save_delta``; a fresh base every ``base_every`` passes bounds replay
  length and lets retention reclaim old chains),
- metric/AUC registry state and the join/update phase bit,
- the pass/step cursor (``BoxPS.pass_id``, ``date``,
  ``Trainer.global_step``),

after first flushing the device tier (pending deferred push applies +
lazily-retained rows — ``Trainer.flush_sparse``), so the snapshot is the
complete post-pass state.

Commit protocol: every member lands atomically (tmp → fsync → replace);
the snapshot's ``MANIFEST.json`` — carrying the cursor, the chain
reference with per-member CRC32s, and checksums of the snapshot's own
files — is written LAST. A snapshot without a committed manifest never
happened; one whose checksums no longer verify is diagnosed and skipped.
``resume`` therefore walks snapshots newest-first and restores the first
one that fully verifies, falling back past a torn/truncated newest
snapshot automatically. ``keep_last_n`` prunes old snapshots (and any
sparse chain directory no surviving snapshot references) after each
successful save.
"""

from __future__ import annotations

import os
import re
import shutil
import time
import warnings
from typing import Any

from paddlebox_tpu import monitor
from paddlebox_tpu.config import flags as config_flags
from paddlebox_tpu.utils import checkpoint as ckpt_lib
from paddlebox_tpu.utils import faultpoint
from paddlebox_tpu.utils import profiler
from paddlebox_tpu.utils.checkpoint import CheckpointCorruptError

_PASS_RE = re.compile(r"^pass-(\d+)$")
_CHAIN_RE = re.compile(r"^chain-(\d+)$")


def _dense_tree(trainer) -> dict[str, Any]:
    return {"params": trainer.params, "opt_state": trainer.opt_state}


def _metric_tree(metrics) -> dict[str, Any]:
    return {name: metrics.get_state(name) for name in metrics.names()}


class PassCheckpointer:
    """Owns one snapshot root. One instance per training job; the driver
    calls :meth:`save` at every pass boundary (directly or through
    ``BoxPS.end_pass``) and :meth:`resume` once at startup."""

    def __init__(self, root: str, keep_last_n: int | None = None,
                 base_every: int | None = None):
        self.root = root
        self.keep_last_n = (config_flags.ckpt_keep_last_n
                            if keep_last_n is None else int(keep_last_n))
        if self.keep_last_n < 2:
            # fallback-past-a-torn-newest needs at least one predecessor
            raise ValueError("keep_last_n must be >= 2 for crash safety")
        self.base_every = (config_flags.ckpt_base_every
                           if base_every is None else int(base_every))
        os.makedirs(root, exist_ok=True)
        self._chain_gen = 0
        self._chain_dir: str | None = None
        self._deltas_in_chain = 0
        # store.save_count as of OUR last save/resume: any foreign
        # save_base/save_delta in between (e.g. FleetUtil donefile models
        # sharing the store) consumed the dirty mask + tombstones, so the
        # next snapshot must be a full base — a delta into our chain
        # would silently miss the rows/evictions the foreign save carried
        # away. The MONOTONIC count is the guard (save_seq can't be: a
        # foreign save_base resets it to 0, aliasing "nothing happened")
        self._expect_count: int | None = None

    # ---- paths -----------------------------------------------------------

    def snap_dir(self, pass_id: int) -> str:
        return os.path.join(self.root, f"pass-{pass_id:05d}")

    def _chain_path(self, name: str) -> str:
        return os.path.join(self.root, name)

    def _list_snaps(self) -> list[tuple[int, str]]:
        out = []
        for n in os.listdir(self.root):
            m = _PASS_RE.match(n)
            if m and os.path.isdir(os.path.join(self.root, n)):
                out.append((int(m.group(1)), os.path.join(self.root, n)))
        return sorted(out)

    # ---- save ------------------------------------------------------------

    def save(self, trainer, box=None, metrics=None,
             pass_id: int | None = None) -> str:
        """Snapshot the complete post-pass state. Returns the snapshot dir.

        Members land atomically in dependency order (sparse chain → dense
        → metrics), manifest last — a kill anywhere before the manifest
        commit leaves this snapshot invisible and the previous one intact.
        """
        t_save0 = time.perf_counter()
        if pass_id is None:
            if box is None:
                raise ValueError("save needs pass_id or a BoxPS")
            pass_id = int(box.pass_id)
        metrics = metrics if metrics is not None else (
            box.metrics if box is not None else None)
        # device tier → host store: pending deferred push apply lands,
        # then unsynced resident rows move D2H (the stager/feed flush the
        # snapshot's completeness depends on)
        trainer.flush_sparse()

        # sparse plane: rotate to a fresh base chain on the first save,
        # every base_every-th pass after, and whenever another writer
        # saved the store since our last snapshot (its delta consumed the
        # dirty rows ours would need — only a full base is still exact)
        rotate = (self._chain_dir is None
                  or (self.base_every > 0
                      and self._deltas_in_chain >= self.base_every - 1)
                  or trainer.store.save_count != self._expect_count)
        # chain bookkeeping commits only AFTER the store save succeeds: a
        # transient failure (ENOSPC, injected IO error) must leave the
        # checkpointer pointing at the last good chain state, not at a
        # half-open baseless chain every later save would trip over
        if rotate:
            gen = self._chain_gen + 1
            chain_name = f"chain-{gen:04d}"
            trainer.store.save_base(self._chain_path(chain_name),
                                    pass_id=pass_id)
            self._chain_gen = gen
            self._chain_dir = chain_name
            self._deltas_in_chain = 0
        else:
            chain_name = self._chain_dir
            trainer.store.save_delta(self._chain_path(chain_name),
                                     pass_id=pass_id)
            self._deltas_in_chain += 1
        save_seq = trainer.store.save_seq
        self._expect_count = trainer.store.save_count
        chain_manifest = ckpt_lib.read_manifest(self._chain_path(chain_name))
        chain_files = {
            name: chain_manifest["files"][name]
            for name in (["base.npz"]
                         + [f"delta-{i:05d}.npz"
                            for i in range(1, save_seq + 1)])}

        snap = self.snap_dir(pass_id)
        os.makedirs(snap, exist_ok=True)
        files: dict[str, dict] = {}
        dense_f = os.path.join(snap, "dense.npz")
        ckpt_lib.save_pytree(_dense_tree(trainer), dense_f)
        files["dense.npz"] = ckpt_lib.file_entry(dense_f)
        if metrics is not None and metrics.names():
            met_f = os.path.join(snap, "metrics.npz")
            ckpt_lib.save_pytree(_metric_tree(metrics), met_f)
            files["metrics.npz"] = ckpt_lib.file_entry(met_f)

        cursor = {
            "pass_id": int(pass_id),
            "global_step": int(trainer.global_step),
            "date": None if box is None else box.date,
            "phase": None if metrics is None else int(metrics.phase),
        }
        faultpoint.hit("pass_ckpt.pre_manifest")
        ckpt_lib.write_manifest(
            snap, files, cursor=cursor, save_seq=save_seq,
            chain_dir=chain_name, chain_files=chain_files,
            parent_snapshot=(f"pass-{pass_id - 1:05d}"
                             if pass_id > 1 else None))
        faultpoint.hit("pass_ckpt.post_manifest")
        # checkpoint lifecycle telemetry: duration + bytes per save, plus
        # a chrome-trace instant so the timeline reads commit points
        seconds = time.perf_counter() - t_save0
        sparse_member = ("base.npz" if rotate
                         else f"delta-{save_seq:05d}.npz")
        nbytes = (sum(e["bytes"] for e in files.values())
                  + chain_files[sparse_member]["bytes"])
        monitor.counter_add("ckpt.saves")
        monitor.counter_add("ckpt.save_seconds", seconds)
        monitor.counter_add("ckpt.bytes", nbytes)
        if rotate:
            monitor.counter_add("ckpt.base_rotations")
        monitor.event("checkpoint_save", type="lifecycle",
                      snapshot=os.path.basename(snap), seconds=seconds,
                      bytes=int(nbytes), rotated=bool(rotate),
                      chain=chain_name, save_seq=int(save_seq))
        profiler.record_instant("checkpoint_commit",
                                {"snapshot": os.path.basename(snap)})
        self._prune()
        return snap

    # ---- discovery / verification ---------------------------------------

    def _verify_snapshot(self, snap: str) -> dict:
        """Full snapshot verification: manifest present, snapshot members
        checksum clean, and the sparse chain prefix it references intact
        — against the CRCs the snapshot itself recorded (the chain dir's
        live manifest may already describe a newer save)."""
        manifest = ckpt_lib.verify_manifest(snap)
        try:
            int(manifest["cursor"]["pass_id"])     # resume depends on it
            int(manifest["cursor"]["global_step"])
            chain_dir = self._chain_path(manifest["chain_dir"])
            need = (["base.npz"]
                    + [f"delta-{i:05d}.npz"
                       for i in range(1, int(manifest["save_seq"]) + 1)])
        except (KeyError, TypeError, ValueError) as e:
            raise CheckpointCorruptError(
                os.path.join(snap, ckpt_lib.MANIFEST_NAME),
                f"snapshot manifest missing/invalid field ({e!r})")
        chain_files = manifest.get("chain_files", {})
        try:
            # same missing/size/crc checks as any manifest, but against
            # the CRCs the SNAPSHOT recorded
            ckpt_lib.verify_manifest(chain_dir, {"files": chain_files},
                                     only=need)
        except CheckpointCorruptError as e:
            name = os.path.basename(e.fname)
            pos = need.index(name) if name in need else -1
            raise CheckpointCorruptError(
                e.fname,
                f"chain member #{pos} of base+{len(need) - 1} deltas "
                f"(as recorded by snapshot {os.path.basename(snap)}): "
                f"{e}") from e
        return manifest

    def latest_valid(self) -> tuple[int, str, dict] | None:
        """Newest snapshot that fully verifies, walking past torn ones
        (with a warning naming the diagnosis). None = nothing to resume."""
        for pass_id, snap in reversed(self._list_snaps()):
            try:
                return pass_id, snap, self._verify_snapshot(snap)
            except CheckpointCorruptError as e:
                # flaky-storage observability: a torn snapshot shows up in
                # the flight record / exposition, not only in this warning
                monitor.counter_add("ckpt.torn_fallbacks")
                monitor.event("checkpoint_torn_fallback", type="lifecycle",
                              snapshot=os.path.basename(snap),
                              error=str(e)[:300])
                warnings.warn(
                    f"snapshot {snap} failed verification ({e}); falling "
                    f"back to the previous one")
        return None

    # ---- resume ----------------------------------------------------------

    def resume(self, trainer, box=None, metrics=None) -> dict | None:
        """Restore every plane from the newest valid snapshot; return its
        cursor dict ({pass_id, global_step, date, phase}), or None when no
        valid snapshot exists (fresh start). The driver re-enters its pass
        loop at ``cursor['pass_id'] + 1``."""
        t_res0 = time.perf_counter()
        found = self.latest_valid()
        if found is None:
            return None
        pass_id, snap, manifest = found
        cursor = dict(manifest["cursor"])
        chain_name = manifest["chain_dir"]
        seq = int(manifest["save_seq"])

        # sparse plane, in place: mutation_count bump invalidates any
        # device-resident rows the feed manager still holds. Chain already
        # verified against the snapshot's own CRCs above.
        trainer.store.restore(self._chain_path(chain_name),
                              upto_seq=seq, verify=False)

        # dense plane (mode-aware: allreduce/kstep/async via restore_dense)
        dense = ckpt_lib.load_pytree(
            _dense_tree(trainer), os.path.join(snap, "dense.npz"))
        trainer.restore_dense(dense["params"], dense["opt_state"])
        trainer.global_step = int(cursor["global_step"])

        metrics = metrics if metrics is not None else (
            box.metrics if box is not None else None)
        if metrics is not None and "metrics.npz" in manifest["files"]:
            states = ckpt_lib.load_pytree(
                _metric_tree(metrics), os.path.join(snap, "metrics.npz"))
            for name, state in states.items():
                metrics.set_state(name, state)
            if cursor.get("phase") is not None:
                metrics.phase = int(cursor["phase"])
        if box is not None:
            box.pass_id = int(cursor["pass_id"])
            box.in_pass = False
            if cursor.get("date") is not None:
                box.date = int(cursor["date"])

        # continue the chain where the snapshot left it: the next save
        # deltas into the same chain dir (store._save_seq was set by
        # restore; stale higher-numbered deltas from the crashed run get
        # overwritten as the re-run reaches them)
        self._chain_dir = chain_name
        self._chain_gen = max(self._chain_gen,
                              int(_CHAIN_RE.match(chain_name).group(1)))
        self._deltas_in_chain = seq
        # store.restore replayed the chain and left save_seq at `seq`; a
        # foreign save between now and our next snapshot bumps save_count
        # and forces the rotation
        self._expect_count = trainer.store.save_count
        seconds = time.perf_counter() - t_res0
        monitor.counter_add("ckpt.resumes")
        monitor.counter_add("ckpt.resume_seconds", seconds)
        monitor.event("checkpoint_resume", type="lifecycle",
                      snapshot=os.path.basename(snap), seconds=seconds,
                      resumed_pass=int(cursor["pass_id"]),
                      chain=chain_name, save_seq=seq)
        return cursor

    # ---- retention -------------------------------------------------------

    def _prune(self) -> None:
        """Drop snapshots beyond keep_last_n, then chain dirs no surviving
        snapshot references. Never touches the open chain."""
        snaps = self._list_snaps()
        for _, snap in snaps[:-self.keep_last_n]:
            shutil.rmtree(snap, ignore_errors=True)
        referenced = {self._chain_dir}
        for _, snap in self._list_snaps():
            try:
                m = ckpt_lib.read_manifest(snap)
            except CheckpointCorruptError:
                continue     # unusable snapshot; resume skips it too
            if m is not None:
                referenced.add(m.get("chain_dir"))
        for n in os.listdir(self.root):
            if _CHAIN_RE.match(n) and n not in referenced:
                shutil.rmtree(os.path.join(self.root, n),
                              ignore_errors=True)
