from paddlebox_tpu.parallel.mesh import (make_mesh, table_sharding,  # noqa: F401
                                         batch_sharding, replicated_sharding)
from paddlebox_tpu.parallel.dense_sync import (AsyncDenseTable,  # noqa: F401
                                               flatten_dense)
