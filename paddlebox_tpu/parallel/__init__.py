from paddlebox_tpu.parallel.mesh import (make_mesh, table_sharding,  # noqa: F401
                                         batch_sharding, replicated_sharding)
