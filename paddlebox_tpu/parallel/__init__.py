from paddlebox_tpu.parallel.mesh import (make_mesh, table_sharding,  # noqa: F401
                                         batch_sharding, replicated_sharding)
from paddlebox_tpu.parallel.dense_sync import (AsyncDenseTable,  # noqa: F401
                                               flatten_dense)
from paddlebox_tpu.parallel.pipeline import (gpipe_spmd,  # noqa: F401
                                             make_pipeline, split_stages,
                                             stack_stage_params)
from paddlebox_tpu.parallel import tensor  # noqa: F401
from paddlebox_tpu.parallel import expert  # noqa: F401
