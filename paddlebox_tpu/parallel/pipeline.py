"""Pipeline parallelism: GPipe-style microbatched stage execution.

The reference runs pipelines with ``PipelineTrainer`` + ``SectionWorker``
(trainer.h:281-309, device_worker.h:541-583): the program is split into
sections by ``BoxPSOptimizer._split_program``'s cut_list (optimizer.py:5374-
5450), each section owns a device, and microbatch scopes flow section to
section over queues.

TPU-native redesign: the "sections" are one jitted stage function whose
parameters are stacked with a leading stage axis and sharded over a ``pp``
mesh axis; activations hop stage→stage with ``lax.ppermute`` over ICI
neighbor links inside ``shard_map``; the microbatch loop is a ``lax.scan``.
Because every op in the schedule (scan, ppermute, dynamic slices) is
differentiable, ``jax.grad`` of a loss around :func:`gpipe_spmd` yields the
reverse pipeline schedule automatically — there is no hand-written backward
section the way SectionWorker replays backward ops.

The schedule is plain GPipe: with S stages and M microbatches the loop runs
M+S-1 ticks, every stage computes each tick, and the bubble fraction is
(S-1)/(M+S-1) — pick M >= 4*S to amortize. Stages must be shape-homogeneous
(activation in == activation out), which CTR towers with equal hidden widths
satisfy; heterogeneous cuts belong at the model level (pad widths).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PP_AXIS = "pp"


def split_stages(layers: Sequence[Any],
                 num_stages: int | None = None,
                 cut_list: Sequence[int] | None = None) -> list[list[Any]]:
    """Group a flat layer list into pipeline stages.

    Mirrors BoxPSOptimizer cut_list semantics (optimizer.py:5374): cut_list
    gives the index of the first layer of each stage after the zeroth.
    Without a cut_list, layers split into ``num_stages`` near-equal groups.
    """
    n = len(layers)
    if cut_list is not None:
        cuts = [0, *cut_list, n]
        if any(b <= a for a, b in zip(cuts[:-1], cuts[1:])):
            raise ValueError(
                f"cut_list {cut_list} must be strictly increasing within "
                f"(0,{n}) — every stage needs at least one layer")
        return [list(layers[a:b]) for a, b in zip(cuts[:-1], cuts[1:])]
    if not num_stages or num_stages < 1:
        raise ValueError("need num_stages or cut_list")
    if num_stages > n:
        raise ValueError(f"num_stages={num_stages} > {n} layers — every "
                         f"stage needs at least one layer")
    bounds = np.linspace(0, n, num_stages + 1).round().astype(int)
    return [list(layers[a:b]) for a, b in zip(bounds[:-1], bounds[1:])]


def stack_stage_params(per_stage: Sequence[Any]) -> Any:
    """Stack per-stage pytrees (identical structure) along a new leading
    stage axis — the array the ``pp`` mesh axis shards."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def gpipe_spmd(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
               stage_params: Any,
               x: jnp.ndarray,
               num_microbatches: int,
               axis_name: str = PP_AXIS) -> jnp.ndarray:
    """Run the GPipe schedule. Call inside shard_map over ``axis_name``.

    stage_params : this device's stage slice — pytree whose leaves carry a
                   leading stage axis of local size 1 (shard_map slicing of
                   the stacked params).
    x            : (B, ...) this device's full local batch (replicated over
                   the pp axis; shard it over dp when composing with data
                   parallelism).
    Returns stage S-1's outputs for all microbatches, reassembled to (B, ...)
    and replicated over the pp axis.
    """
    S = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], stage_params)  # drop stage axis
    M = num_microbatches
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    mb = B // M
    xm = x.reshape(M, mb, *x.shape[1:])

    fwd_perm = [(d, (d + 1) % S) for d in range(S)]

    def tick(carry, t):
        recv, outs = carry
        # stage 0 feeds microbatch t (clamped — garbage ticks are masked at
        # the output write); later stages consume what arrived last tick
        x_t = lax.dynamic_index_in_dim(xm, jnp.clip(t, 0, M - 1), 0,
                                       keepdims=False)
        inp = jnp.where(stage == 0, x_t.astype(recv.dtype), recv)
        y = stage_fn(params, inp)
        # rotate activations one hop forward around the ring
        recv_next = lax.ppermute(y, axis_name, perm=fwd_perm)
        # last stage banks microbatch t-(S-1) once it's real; bubble ticks
        # (slot < 0) clamp to slot 0 and write zeros over its initial zeros,
        # then t = S-1 overwrites slot 0 with the real first microbatch
        slot = t - (S - 1)
        y_masked = jnp.where(slot >= 0, y, 0.0)
        outs = lax.dynamic_update_index_in_dim(
            outs, y_masked, jnp.clip(slot, 0, M - 1), 0)
        return (recv_next, outs), None

    # the ring constrains activations to one shape: stage input == stage
    # output == a microbatch of x (pad widths at the model level otherwise).
    # Deriving the zero inits from x keeps whatever other mesh axes x varies
    # over (e.g. dp) in their type; pcast adds the pp axis.
    vary = lambda a: lax.pcast(a, axis_name, to="varying")
    recv0 = vary(xm[0] * 0.0)
    outs0 = vary(xm * 0.0)
    (_, outs), _ = lax.scan(tick, (recv0, outs0), jnp.arange(M + S - 1))
    # only stage S-1 holds real outputs; psum broadcasts them to the whole
    # pp ring so downstream loss code is stage-agnostic
    outs = lax.psum(jnp.where(stage == S - 1, outs, 0.0), axis_name)
    return outs.reshape(B, *x.shape[1:])


def make_pipeline(mesh: Mesh,
                  stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                  num_microbatches: int,
                  pp_axis: str = PP_AXIS,
                  dp_axis: str | None = None):
    """Jitted pipelined apply over GLOBAL arrays.

    Returns ``fn(stacked_params, x) -> y`` where stacked_params' leaves carry
    the leading stage axis (sharded over ``pp_axis``) and x/y are the global
    batch (sharded over ``dp_axis`` when given, replicated otherwise). The
    returned fn is differentiable — wrap a loss and ``jax.grad`` it to train.
    """
    param_spec = P(pp_axis)
    batch_spec = P(dp_axis) if dp_axis else P()

    def body(stacked, x):
        return gpipe_spmd(stage_fn, stacked, x, num_microbatches,
                          axis_name=pp_axis)

    # specs are prefix pytrees: one spec covers every leaf of the params tree
    fn = jax.shard_map(body, mesh=mesh, in_specs=(param_spec, batch_spec),
                       out_specs=batch_spec)
    return jax.jit(fn, out_shardings=NamedSharding(mesh, batch_spec))


def mlp_stage_fn(activation: Callable[[jnp.ndarray], jnp.ndarray]
                 = jax.nn.relu):
    """Stage function for a homogeneous dense tower: params
    {"w": (L, D, D), "b": (L, D)} — L layers per stage, width D."""
    def fn(params, x):
        def layer(h, wb):
            w, b = wb
            return activation(
                jnp.dot(h, w, preferred_element_type=jnp.float32) + b), None
        h, _ = lax.scan(layer, x, (params["w"], params["b"]))
        return h
    return fn
