"""Device-mesh construction and canonical shardings.

The reference's parallel topology is hand-built: one worker thread per GPU,
NCCL rings intra-node, boxps SyncDense/MPI inter-node (SURVEY.md §2.3). Here
the topology is a `jax.sharding.Mesh` with up to two axes:

- ``"node"`` — the DCN axis (hosts); present only multi-host.
- ``"dp"``   — the ICI axis (chips per host); data parallelism AND the
  embedding-table shard axis ride this (the reference likewise shards the
  embedding across the same GPUs that run data-parallel training).

A 2D (node, dp) psum gives the reference's hierarchical
reduce-scatter → inter-node sync → all-gather (boxps_worker.cc:497-511) for
free — XLA emits exactly that decomposition for multi-axis collectives.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names
NODE_AXIS = "node"
DP_AXIS = "dp"


def make_mesh(num_devices: int | None = None,
              num_nodes: int = 1,
              devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build the (node, dp) mesh.

    Single-host: a 1D ("dp",) mesh over local devices. Multi-host (or
    simulated multi-node): 2D ("node", "dp").
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if num_devices is not None:
        devs = devs[:num_devices]
    n = len(devs)
    if num_nodes > 1:
        if n % num_nodes:
            raise ValueError(f"{n} devices not divisible by {num_nodes} nodes")
        arr = np.array(devs).reshape(num_nodes, n // num_nodes)
        return Mesh(arr, (NODE_AXIS, DP_AXIS))
    return Mesh(np.array(devs), (DP_AXIS,))


def shard_axes(mesh: Mesh) -> tuple[str, ...]:
    """All mesh axes, in order — the embedding table shards over the product."""
    return tuple(mesh.axis_names)


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Embedding working-set table: rows contiguously sharded over all axes."""
    return NamedSharding(mesh, P(shard_axes(mesh)))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Per-example batch arrays: leading dim sharded over all axes (pure DP)."""
    return NamedSharding(mesh, P(shard_axes(mesh)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Superstep operands (k, B, ...): the scan axis 0 replicated, the
    batch axis 1 sharded over all mesh axes."""
    return NamedSharding(mesh, P(None, shard_axes(mesh)))


def num_shards(mesh: Mesh) -> int:
    return int(np.prod(mesh.devices.shape))
