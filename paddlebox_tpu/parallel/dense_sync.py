"""Dense-parameter sync strategies.

The reference trains dense params in one of three modes per
``BoxPSWorkerParameter.sync_mode`` (trainer_desc.proto:100-108,
boxps_worker.cc:481-521):

- **allreduce per step** — grads pmean'd every step (DenseKStepALL with k=1,
  also the ``c_mixallgather`` fused-buffer op). Trainer default.
- **K-step parameter averaging** — each worker updates its own dense copy
  with purely local grads; every K steps the *parameters* are averaged
  (``SyncParam``: ncclAllReduce of the flat param tensor scaled by 1/n,
  boxps_worker.cc:481-521 — local-SGD semantics). On a 2D (node, dp) mesh a
  single pmean reproduces the reference's hierarchical
  reduce-scatter → inter-node SyncDense → all-gather decomposition.
- **async host dense table** — ``BoxPSAsynDenseTable`` (device_worker.h:586,
  boxps_worker.cc:37-296): workers pull the whole flat param vector and push
  flat grads through queues; a background host thread merges up to
  ``merge_limit`` pending grads and applies a hand-rolled Adam-like update
  (hard-coded betas 0.99/0.9999, cc:173-225) with optional per-parameter
  learning rates (``BoxWrapper::GetLRMap``).

This module provides the host-side async table and the flat-vector
utilities; the Trainer wires the modes into its jitted step.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

import numpy as np

import jax

from paddlebox_tpu.monitor import context as mon_ctx
from jax.flatten_util import ravel_pytree


def flatten_dense(params) -> tuple[np.ndarray, Callable]:
    """Pytree → (flat float32 numpy vector, unravel fn) — the reference's
    single ``param_sync_`` tensor aliasing every dense param
    (boxps_worker.cc:453-472)."""
    flat, unravel = ravel_pytree(params)
    return np.asarray(flat, dtype=np.float32), unravel


def make_dense_packer(params_template, opt_template):
    """(pack, unpack, n_args): flatten the dense params and the f32
    leaves of the optimizer state into TWO flat vectors plus the non-f32
    aux leaves (optimizer step counts).

    Why: every jitted-step argument leaf costs host-side dispatch
    processing; a DeepFM trainer carries ~30 dense-state leaves and the
    consolidation measured 0.6ms/step on a tunneled v5e (the reference
    aliases all dense params into one param_sync_ tensor for the same
    reason, boxps_worker.cc:453-472). pack/unpack are jit-traceable —
    inside the step they are free reshapes/slices fused by XLA — and
    exact: unpack(pack(x)) == x leaf for leaf.

    Returns None when a params leaf is not float32 (no flat fast path).
    """
    import jax.numpy as jnp

    p_leaves = jax.tree.leaves(params_template)
    if any(l.dtype != jnp.float32 for l in p_leaves):
        return None
    _, unravel_p = ravel_pytree(params_template)
    o_leaves, o_def = jax.tree.flatten(opt_template)
    is_f32 = [l.dtype == jnp.float32 for l in o_leaves]
    f32_shapes = [l.shape for l, m in zip(o_leaves, is_f32) if m]
    f32_sizes = [int(np.prod(s)) if s else 1 for s in f32_shapes]
    n_aux = sum(1 for m in is_f32 if not m)

    def pack(params, opt_state):
        pf = ravel_pytree(params)[0]
        leaves = jax.tree.leaves(opt_state)
        f32s = [jnp.ravel(l) for l, m in zip(leaves, is_f32) if m]
        of = (jnp.concatenate(f32s) if f32s
              else jnp.zeros((0,), jnp.float32))
        aux = tuple(l for l, m in zip(leaves, is_f32) if not m)
        return (pf, of, *aux)

    def unpack(state):
        pf, of = state[0], state[1]
        aux = state[2:]
        params = unravel_p(pf)
        out, off, ai, fi = [], 0, 0, 0
        for m in is_f32:
            if m:
                sz, sh = f32_sizes[fi], f32_shapes[fi]
                out.append(of[off:off + sz].reshape(sh))
                off += sz
                fi += 1
            else:
                out.append(aux[ai])
                ai += 1
        return params, jax.tree.unflatten(o_def, out)

    return pack, unpack, 2 + n_aux


class AsyncDenseTable:
    """Host-resident async dense parameter server (BoxPSAsynDenseTable).

    Staleness semantics match the reference: pulls return the latest applied
    params without waiting for in-flight grads; the updater thread merges up
    to ``merge_limit`` queued grads into one update step.
    """

    def __init__(self, flat_params: np.ndarray, lr: float = 1e-3,
                 betas: tuple[float, float] = (0.99, 0.9999),
                 eps: float = 1e-8, merge_limit: int = 4,
                 lr_map: list[tuple[slice, float]] | None = None):
        self._params = np.array(flat_params, dtype=np.float32)
        self._mom1 = np.zeros_like(self._params)
        self._mom2 = np.zeros_like(self._params)
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.merge_limit = max(1, merge_limit)
        # per-range LR overrides (the GetLRMap per-param-name map, flattened;
        # (slice, lr) pairs — slices aren't hashable before 3.12)
        self._lr_vec = np.full_like(self._params, lr)
        for sl, r in (lr_map or []):
            self._lr_vec[sl] = r
        self._queue: queue.Queue[np.ndarray | None] = queue.Queue()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self.steps_applied = 0
        self.grads_merged = 0

    # ---- worker side ----

    def pull(self) -> np.ndarray:
        with self._lock:
            return self._params.copy()

    def push(self, flat_grad: np.ndarray) -> None:
        self._queue.put(np.asarray(flat_grad, dtype=np.float32))

    # ---- updater thread (ThreadUpdate, boxps_worker.cc:173-225) ----

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = mon_ctx.spawn(self._run, name="async-dense-table")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._queue.put(None)
        self._thread.join()
        self._thread = None

    def flush(self) -> None:
        """Block until every grad pushed so far has been applied."""
        self._queue.join()

    def _run(self) -> None:
        while True:
            grad = self._queue.get()
            if grad is None:
                self._queue.task_done()
                return
            merged, n = grad, 1
            # merge whatever else is already waiting, up to the limit
            while n < self.merge_limit:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._apply(merged, n)
                    for _ in range(n + 1):  # n grads + the stop sentinel
                        self._queue.task_done()
                    return
                merged = merged + nxt
                n += 1
            self._apply(merged, n)
            for _ in range(n):
                self._queue.task_done()

    # ---- checkpoint plane (the dense half of SaveBase/LoadModel) ----

    def state_dict(self) -> dict[str, np.ndarray]:
        with self._lock:
            return {"params": self._params.copy(),
                    "mom1": self._mom1.copy(), "mom2": self._mom2.copy(),
                    "steps": np.asarray([self.steps_applied])}

    def load_state_dict(self, state: dict[str, np.ndarray]) -> None:
        with self._lock:
            self._params[:] = state["params"]
            self._mom1[:] = state["mom1"]
            self._mom2[:] = state["mom2"]
            self.steps_applied = int(np.asarray(state["steps"]).reshape(-1)[0])

    def _apply(self, grad_sum: np.ndarray, n: int) -> None:
        g = grad_sum / n
        b1, b2 = self.betas
        with self._lock:
            self._mom1 *= b1
            self._mom1 += (1 - b1) * g
            self._mom2 *= b2
            self._mom2 += (1 - b2) * g * g
            self._params -= self._lr_vec * self._mom1 / (
                np.sqrt(self._mom2) + self.eps)
            self.steps_applied += 1
            self.grads_merged += n


def stack_for_shards(params, n_shards: int):
    """Replicate a pytree along a new leading shard axis — per-device dense
    copies for K-step local training (the reference gives each GPU its own
    dense params between syncs, boxps_worker.cc:403-480)."""
    return jax.tree.map(
        lambda a: np.broadcast_to(np.asarray(a)[None],
                                  (n_shards,) + np.shape(a)).copy(), params)
