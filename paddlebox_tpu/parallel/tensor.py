"""Tensor (model) parallelism — Megatron-style sharded linears.

Absent in the reference (SURVEY.md §2.3: "Tensor parallelism — NO"); added
here because on TPU it is a mesh axis away, and CTR towers are starting to
grow past single-chip widths. The classic pairing over a ``tp`` axis:

- column-parallel linear: W1 split along OUT features; each shard computes
  its slice of the hidden layer, no communication (inputs replicated).
- row-parallel linear: W2 split along IN features; each shard computes a
  partial product and one ``psum`` over tp restores the full output.

One all-reduce per column→row block — the standard Megatron fwd cost. The
pattern composes with dp: use a 2D (dp, tp) mesh, batch sharded over dp,
weights sharded over tp.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

TP_AXIS = "tp"


def make_tp_mesh(n_tp: int, n_dp: int = 1,
                 devices: Sequence[jax.Device] | None = None) -> Mesh:
    devs = list(devices) if devices is not None else list(jax.devices())
    if n_dp > 1:
        arr = np.array(devs[:n_dp * n_tp]).reshape(n_dp, n_tp)
        return Mesh(arr, ("dp", TP_AXIS))
    return Mesh(np.array(devs[:n_tp]), (TP_AXIS,))


def init_tp_mlp(key, dims: Sequence[int]) -> list[dict[str, jnp.ndarray]]:
    """Unsharded parameters for a [d0, d1, ..., dn] MLP (relu between,
    linear head). Shard with `shard_tp_params` or feed to the reference
    apply for parity tests."""
    params = []
    keys = jax.random.split(key, len(dims) - 1)
    for k, din, dout in zip(keys, dims[:-1], dims[1:]):
        params.append({
            "w": jax.random.normal(k, (din, dout), jnp.float32)
            / jnp.sqrt(din),
            "b": jnp.zeros((dout,), jnp.float32),
        })
    return params


def mlp_reference(params: list[dict], x: jnp.ndarray) -> jnp.ndarray:
    h = x
    for i, p in enumerate(params):
        h = h @ p["w"] + p["b"]
        if i < len(params) - 1:
            h = jax.nn.relu(h)
    return h


def plan_modes(dims: Sequence[int], n_tp: int) -> list[str]:
    """Per-layer parallel mode: "col" (OUT sharded), "row" (IN sharded,
    psum), or "rep" (replicated — e.g. a width-1 head).

    Greedy: col whenever features are complete and OUT divides by tp; row
    whenever features arrive sharded (its IN is the previous col's OUT,
    divisible by construction); rep otherwise. A col layer is therefore
    always followed by a row layer — no gathers are ever needed."""
    modes, sharded = [], False
    for din, dout in zip(dims[:-1], dims[1:]):
        if sharded:
            modes.append("row")
            sharded = False
        elif dout % n_tp == 0:
            modes.append("col")
            sharded = True
        else:
            modes.append("rep")
    return modes


_SPECS = {
    "col": {"w": P(None, TP_AXIS), "b": P(TP_AXIS)},
    "row": {"w": P(TP_AXIS, None), "b": P()},
    "rep": {"w": P(), "b": P()},
}


def shard_tp_params(mesh: Mesh, params: list[dict]) -> list[dict]:
    """Place weights per the mode plan (col: OUT split + sharded bias;
    row: IN split + replicated bias; rep: replicated)."""
    n_tp = mesh.shape[TP_AXIS]
    dims = [params[0]["w"].shape[0]] + [p["w"].shape[1] for p in params]
    out = []
    for p, mode in zip(params, plan_modes(dims, n_tp)):
        spec = _SPECS[mode]
        out.append({"w": jax.device_put(p["w"], NamedSharding(mesh,
                                                              spec["w"])),
                    "b": jax.device_put(p["b"], NamedSharding(mesh,
                                                              spec["b"]))})
    return out


def make_tp_mlp(mesh: Mesh, dims: Sequence[int],
                dp_axis: str | None = None) -> Callable:
    """→ fn(sharded_params, x) running the planned col/row/rep MLP under
    shard_map with one psum per row layer; numerically equal to
    `mlp_reference`.

    x is replicated over tp (and, if `dp_axis` given, sharded over dp)."""
    batch_spec = P(dp_axis) if dp_axis else P()
    n_tp = mesh.shape[TP_AXIS]
    modes = plan_modes(dims, n_tp)
    n_layers = len(modes)

    def body(params: list[dict], x: jnp.ndarray) -> jnp.ndarray:
        h = x
        for i, (p, mode) in enumerate(zip(params, modes)):
            if mode == "row":
                # partial product + one tp all-reduce
                h = lax.psum(h @ p["w"], TP_AXIS) + p["b"]
            else:  # col (local OUT slice) or rep (replicated)
                h = h @ p["w"] + p["b"]
            if i < n_layers - 1:
                # relu is elementwise — valid on column-sharded features
                # (each shard holds complete individual features)
                h = jax.nn.relu(h)
        return h

    in_specs = ([_SPECS[m] for m in modes], batch_spec)
    dp = dp_axis if dp_axis else None
    # a trailing col layer leaves the feature axis sharded over tp
    out_spec = P(dp, TP_AXIS) if modes[-1] == "col" else batch_spec

    # jitted once — rebuilding per call would retrace every step
    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_spec))
