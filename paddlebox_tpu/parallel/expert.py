"""Expert parallelism — MoE with all_to_all token dispatch over an ep axis.

Absent in the reference (SURVEY.md §2.3: "Expert parallelism — NO"; its
MMoE runs every expert densely on every device). Here experts shard across
an ``ep`` mesh axis and tokens travel to their experts through the same
fixed-capacity ``all_to_all`` pattern the embedding engine uses for keys
(embedding/sharded.py) — the TPU-native shape of MoE dispatch:

    gate (top-k softmax) → route token features into per-(device, expert)
    capacity lanes → all_to_all over ep → batched expert MLPs
    (one einsum over stacked local experts) → all_to_all back →
    weighted combine.

Tokens beyond a lane's capacity are dropped (standard MoE capacity-factor
semantics; monitor with `dropped_tokens`). Numerics match `moe_reference`
for all surviving tokens.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

EP_AXIS = "ep"


def make_ep_mesh(n_ep: int,
                 devices: Sequence[jax.Device] | None = None) -> Mesh:
    devs = list(devices) if devices is not None else list(jax.devices())
    return Mesh(np.array(devs[:n_ep]), (EP_AXIS,))


def init_moe(key, num_experts: int, d_model: int, d_hidden: int) -> dict:
    """Gate + stacked expert FFNs (unsharded; shard with shard_moe_params)."""
    kg, k1, k2 = jax.random.split(key, 3)
    s1 = 1.0 / jnp.sqrt(d_model)
    s2 = 1.0 / jnp.sqrt(d_hidden)
    return {
        "gate": jax.random.normal(kg, (d_model, num_experts),
                                  jnp.float32) * s1,
        "w1": jax.random.normal(k1, (num_experts, d_model, d_hidden),
                                jnp.float32) * s1,
        "b1": jnp.zeros((num_experts, d_hidden), jnp.float32),
        "w2": jax.random.normal(k2, (num_experts, d_hidden, d_model),
                                jnp.float32) * s2,
        "b2": jnp.zeros((num_experts, d_model), jnp.float32),
    }


def _expert_ffn(w1, b1, w2, b2, x):
    """x (E, n, D) through per-expert FFNs — one batched einsum pair."""
    h = jax.nn.relu(jnp.einsum("end,edh->enh", x, w1) + b1[:, None, :])
    return jnp.einsum("enh,ehd->end", h, w2) + b2[:, None, :]


def moe_reference(params: dict, x: jnp.ndarray, top_k: int = 2
                  ) -> jnp.ndarray:
    """Dense ground truth: every expert computes every token."""
    logits = x @ params["gate"]
    weights, experts = lax.top_k(jax.nn.softmax(logits, axis=-1), top_k)
    all_out = _expert_ffn(params["w1"], params["b1"], params["w2"],
                          params["b2"],
                          jnp.broadcast_to(x, (params["w1"].shape[0],
                                               *x.shape)))
    out = jnp.zeros_like(x)
    for k in range(top_k):
        out = out + weights[:, k:k + 1] * all_out[experts[:, k],
                                                  jnp.arange(x.shape[0])]
    return out


def shard_moe_params(mesh: Mesh, params: dict) -> dict:
    """Experts shard over ep (leading axis); the gate is replicated."""
    ex = NamedSharding(mesh, P(EP_AXIS))
    rep = NamedSharding(mesh, P())
    return {
        "gate": jax.device_put(params["gate"], rep),
        "w1": jax.device_put(params["w1"], ex),
        "b1": jax.device_put(params["b1"], ex),
        "w2": jax.device_put(params["w2"], ex),
        "b2": jax.device_put(params["b2"], ex),
    }


def dropped_tokens(params: dict, x: jnp.ndarray, n_ep: int,
                   top_k: int = 2, capacity_factor: float = 2.0) -> int:
    """How many (token, choice) assignments the dispatch will drop.

    Mirrors make_moe exactly: each top-k round has its OWN capacity lanes
    (a separate all_to_all per k), so counts are per (source device,
    expert, k)."""
    logits = x @ params["gate"]
    _, experts = lax.top_k(jax.nn.softmax(logits, axis=-1), top_k)
    E = params["w1"].shape[0]
    n_local = x.shape[0] // n_ep
    cap = _capacity(n_local, E, capacity_factor)
    dropped = 0
    for k in range(top_k):
        for dev in range(n_ep):
            loc = np.asarray(experts[dev * n_local:(dev + 1) * n_local, k])
            counts = np.bincount(loc, minlength=E)
            dropped += int(np.maximum(counts - cap, 0).sum())
    return dropped


def _capacity(n_local: int, n_experts: int, factor: float) -> int:
    avg = n_local * 1.0 / n_experts  # per (local batch, expert) average
    return max(1, int(np.ceil(avg * factor)))


def make_moe(mesh: Mesh, num_experts: int, top_k: int = 2,
             capacity_factor: float = 2.0) -> Callable:
    """→ fn(sharded_params, x) with x batch-sharded over ep.

    Requires num_experts % n_ep == 0."""
    n_ep = mesh.shape[EP_AXIS]
    if num_experts % n_ep:
        raise ValueError(f"{num_experts} experts not divisible by "
                         f"ep={n_ep}")
    e_local = num_experts // n_ep

    def body(params: dict, x: jnp.ndarray) -> jnp.ndarray:
        n, d = x.shape  # local batch
        cap = _capacity(n, num_experts, capacity_factor)
        logits = x @ params["gate"]
        weights, experts = lax.top_k(jax.nn.softmax(logits, axis=-1), top_k)
        out = jnp.zeros_like(x)
        for k in range(top_k):
            # destination = global expert id; device dev = id // e_local
            # owns local expert id % e_local
            dest = experts[:, k]
            # lane position within each destination: stable rank
            order = jnp.argsort(dest)
            sdest = dest[order]
            counts = jnp.bincount(dest, length=num_experts)
            starts = jnp.cumsum(counts) - counts
            pos = jnp.arange(n, dtype=jnp.int32) - starts[sdest]
            valid = pos < cap
            # send buffers: features + originating row (for the return trip)
            send_x = jnp.zeros((n_ep, e_local, cap, d), x.dtype)
            send_row = jnp.full((n_ep, e_local, cap), -1, jnp.int32)
            sdev, sloc = sdest // e_local, sdest % e_local
            rows = order.astype(jnp.int32)
            send_x = send_x.at[sdev, sloc, pos].set(
                jnp.where(valid[:, None], x[order], 0.0), mode="drop")
            send_row = send_row.at[sdev, sloc, pos].set(
                jnp.where(valid, rows, -1), mode="drop")
            # dispatch / compute / return. After the tiled all_to_all,
            # axis 0 indexes the SOURCE device, so fold (src, cap) into the
            # expert token axis with an explicit transpose — and undo it
            # symmetrically on the way back.
            recv_x = lax.all_to_all(send_x, EP_AXIS, 0, 0, tiled=True)
            recv_x = recv_x.transpose(1, 0, 2, 3).reshape(
                e_local, n_ep * cap, d)
            y = _expert_ffn(params["w1"], params["b1"], params["w2"],
                            params["b2"], recv_x)
            y = y.reshape(e_local, n_ep, cap, d).transpose(1, 0, 2, 3)
            back = lax.all_to_all(y, EP_AXIS, 0, 0, tiled=True)
            # scatter outputs to their originating rows
            flat_row = send_row.reshape(-1)
            flat_y = back.reshape(-1, d)
            safe = jnp.where(flat_row >= 0, flat_row, n)
            gathered = jnp.zeros((n + 1, d), x.dtype).at[safe].add(
                flat_y, mode="drop")[:n]
            out = out + weights[:, k:k + 1] * gathered
        return out

    spec_p = {"gate": P(), "w1": P(EP_AXIS), "b1": P(EP_AXIS),
              "w2": P(EP_AXIS), "b2": P(EP_AXIS)}

    # jitted once — rebuilding per call would retrace every step
    return jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(spec_p, P(EP_AXIS)),
        out_specs=P(EP_AXIS)))
