"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference predates long-sequence towers (SURVEY.md §5: CTR slots are
pooled, never attended over at length), but this framework treats
long-context as first-class: user-behavior towers routinely attend over
10k+ events, and a single chip's HBM bounds S^2 attention. Two standard
TPU-native schemes, both written to run inside ``shard_map`` over a mesh
axis that shards the sequence dimension:

- ``ring_attention`` — K/V blocks rotate around the ring via
  ``lax.ppermute`` while each device keeps its Q shard; softmax is
  accumulated online (flash-style running max/denominator), so memory is
  O(S_local^2) and the K/V transfer rides ICI neighbor links.
- ``ulysses_attention`` — two ``lax.all_to_all``s re-shard from
  sequence-parallel to head-parallel, run full local attention per head
  group, and shard back. Cheaper collectives when heads >= devices.

Both match single-device full attention bit-for-bit up to fp tolerance
(tests/test_sequence_parallel.py) including causal masking and autodiff.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def attention_reference(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        causal: bool = False) -> jnp.ndarray:
    """Plain full attention. Shapes: (B, S, H, D) -> (B, S, H, D)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        S_q, S_k = s.shape[-2], s.shape[-1]
        mask = jnp.arange(S_k)[None, :] > jnp.arange(S_q)[:, None]
        s = jnp.where(mask, -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name, causal: bool = False) -> jnp.ndarray:
    """Ring attention inside shard_map; sequence dim sharded over axis_name.

    q, k, v: (B, S_local, H, D) — this device's sequence shard.
    Returns (B, S_local, H, D), identical to full attention over the global
    sequence. K/V blocks travel the ring once (D-1 ppermutes), overlapping
    compute with neighbor transfers; the online-softmax carry keeps exact
    results without materializing the (S, S) score matrix.
    """
    n_dev = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, S_l, H, Dh = q.shape
    scale = 1.0 / math.sqrt(Dh)
    in_dtype = q.dtype
    q_pos = my * S_l + jnp.arange(S_l)  # global query positions

    def accumulate(o, m, l, kb, vb, i):
        # kb originated on device (my - i) mod n_dev
        src = (my - i) % n_dev
        # scores and the (o, m, l) running state accumulate in f32: with
        # bf16 inputs the corr-rescale + re-sum repeats once per ring hop
        # and would compound bf16 rounding with ring size otherwise
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                       preferred_element_type=jnp.float32) * scale
        mask = None
        if causal:
            k_pos = src * S_l + jnp.arange(S_l)
            mask = k_pos[None, :] > q_pos[:, None]          # (S_l, S_l)
            s = jnp.where(mask[None, None], -jnp.inf, s)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # fully-masked rows keep m = -inf; guard the exp shift
        shift = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - shift[..., None])
        if causal:
            p = jnp.where(mask[None, None], 0.0, p)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), 0.0, m) - shift)
        corr = jnp.where(jnp.isneginf(m), 0.0, corr)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = (o * corr[..., None]
                 + jnp.einsum("bhqk,bkhd->bqhd", p, vb,
                              preferred_element_type=jnp.float32)
                 .transpose(0, 2, 1, 3))
        return o_new, m_new, l_new

    def one_block(carry, i):
        o, m, l, kb, vb = carry
        o, m, l = accumulate(o, m, l, kb, vb, i)
        kb, vb = lax.ppermute(
            (kb, vb), axis_name,
            perm=[(d, (d + 1) % n_dev) for d in range(n_dev)])
        return (o, m, l, kb, vb), None

    # pcast to varying: the zero inits must carry the same device-varying
    # type as the loop outputs or scan rejects the carry
    vary = lambda x: lax.pcast(x, axis_name, to="varying")
    o0 = vary(jnp.zeros((B, H, S_l, Dh), jnp.float32))
    m0 = vary(jnp.full((B, H, S_l), -jnp.inf, jnp.float32))
    l0 = vary(jnp.zeros((B, H, S_l), jnp.float32))
    # D-1 rotations; the final held block is consumed without another hop
    (o, m, l, kb, vb), _ = lax.scan(one_block, (o0, m0, l0, k, v),
                                    jnp.arange(n_dev - 1))
    o, m, l = accumulate(o, m, l, kb, vb, n_dev - 1)
    denom = jnp.where(l == 0.0, 1.0, l)
    out = (o / denom[..., None]).astype(in_dtype)
    return out.transpose(0, 2, 1, 3)  # (B, H, S_l, D) -> (B, S_l, H, D)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name, causal: bool = False) -> jnp.ndarray:
    """Ulysses (all-to-all) attention inside shard_map.

    Re-shards (B, S_local, H, D) sequence-parallel inputs to
    (B, S_global, H_local, D) head-parallel, runs exact full attention on
    each device's head group, and shards back. Requires
    H %% axis_size == 0.
    """
    n_dev = lax.axis_size(axis_name)
    H = q.shape[2]
    if H % n_dev:
        raise ValueError(f"heads {H} not divisible by axis size {n_dev}")

    def to_heads(x):  # split heads, concat sequence
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):    # split sequence, concat heads
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = attention_reference(qh, kh, vh, causal=causal)
    return to_seq(out)


def make_sequence_parallel_attention(mesh: jax.sharding.Mesh, axis_name: str,
                                     mode: str = "ring",
                                     causal: bool = False):
    """Jitted (B, S, H, D) attention with S sharded over `axis_name`.

    The returned fn takes/returns GLOBAL arrays; sharding in/out is
    P(None, axis_name) on the sequence dim — drop-in for a model that was
    using full attention but whose sequences stopped fitting one chip.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    inner = {"ring": ring_attention, "ulysses": ulysses_attention}[mode]
    spec = P(None, axis_name)
    sh = NamedSharding(mesh, spec)

    def body(q, k, v):
        return inner(q, k, v, axis_name, causal=causal)

    fn = jax.jit(jax.shard_map(body, mesh=mesh,
                               in_specs=(spec, spec, spec), out_specs=spec),
                 out_shardings=sh)
    return fn
