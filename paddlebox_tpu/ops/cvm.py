"""Standalone CVM op.

Reference: ``cvm_op`` (operators/cvm_op.{cc,cu}): given per-example feature
rows whose leading two columns are show/click, either apply the log transform
(use_cvm=True) or strip the two columns (use_cvm=False). Appears outside the
fused seqpool path when models consume per-example (already pooled) values.
"""

from __future__ import annotations

import jax.numpy as jnp


def cvm(x: jnp.ndarray, use_cvm: bool = True) -> jnp.ndarray:
    """x (..., D) with x[..., 0]=show, x[..., 1]=click."""
    if not use_cvm:
        return x[..., 2:]
    log_show = jnp.log(x[..., 0:1] + 1.0)
    log_ctr = jnp.log(x[..., 1:2] + 1.0) - log_show
    return jnp.concatenate([log_show, log_ctr, x[..., 2:]], axis=-1)


def cvm_inverse(y: jnp.ndarray) -> jnp.ndarray:
    """Inverse of the log transform (used by tests / debugging)."""
    show = jnp.exp(y[..., 0:1]) - 1.0
    clk = jnp.exp(y[..., 1:2] + y[..., 0:1]) - 1.0
    return jnp.concatenate([show, clk, y[..., 2:]], axis=-1)
