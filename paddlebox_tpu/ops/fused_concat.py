"""Fused concat.

Reference: ``fused_concat`` (operators/fused/fused_concat_op.cu) concatenates
per-slot column ranges of many inputs in one kernel. Under XLA a plain
concatenate fuses identically; the op exists here for API parity and for the
column-range slicing variant (``length``/``offset`` attrs).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp


def fused_concat(xs: Sequence[jnp.ndarray], offset: int = 0,
                 length: int = -1, axis: int = -1) -> jnp.ndarray:
    """Concatenate [x[..., offset:offset+length] for x in xs] along axis."""
    if length >= 0:
        xs = [x[..., offset:offset + length] for x in xs]
    elif offset:
        xs = [x[..., offset:] for x in xs]
    return jnp.concatenate(list(xs), axis=axis)
