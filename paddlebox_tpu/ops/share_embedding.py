"""ShareEmbedding feature type — per-slot selection from a shared w block.

Reference: ``FeaturePullValueGpuShareEmbedding`` /
``FeaturePushValueGpuShareEmbedding`` (dispatch box_wrapper.cc:419-422,
492-495; kernels ``PushCopyBaseShareEmbedding``/``PushMergeCopyBase-
ShareEmbedding`` box_wrapper.cu:543-674): several slots share one key space
and one embedx vector, but the PS row carries a scalar embed weight **per
sharing slot** (``embed_g[SHARE_EMBEDDING_NUM]``) so each slot trains its
own wide/LR component over the shared key.

TPU-native rendering: ``EmbeddingConfig(embed_w_num=N)`` widens the row's w
column into an N-column block (config.py), pulls return
``[show, clk, w_0..w_{N-1}, embedx]``, and :func:`select_share_embedding`
maps that to the standard ``[show, clk, w, embedx]`` view with each slot
reading ITS plane — a take_along_axis whose autodiff scatters each slot's
w-grad back to only its own plane (exactly the reference's per-slot
``embed_g`` routing), while embedx grads from all sharing slots merge on
the common key row.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.embedding.config import EmbeddingConfig


def select_share_embedding(pulled: jnp.ndarray, segment_ids,
                           slot_share_idx, cfg: EmbeddingConfig
                           ) -> jnp.ndarray:
    """(B, T, pull_width) → (B, T, 3 + total_dim) standard pull view.

    segment_ids    : (T,) slot id per token position (SparseLayout)
    slot_share_idx : (num_slots,) which w plane each slot reads, in
                     [0, embed_w_num)
    """
    n = cfg.embed_w_num
    share = jnp.asarray(slot_share_idx, jnp.int32)[
        jnp.asarray(segment_ids, jnp.int32)]                   # (T,)
    w_block = pulled[..., 2:2 + n]                             # (B, T, n)
    w_sel = jnp.take_along_axis(
        w_block, jnp.broadcast_to(share[None, :, None],
                                  (*w_block.shape[:2], 1)), axis=2)
    return jnp.concatenate([pulled[..., :2], w_sel, pulled[..., 2 + n:]],
                           axis=-1)


class ShareEmbeddingModel:
    """Wrap any zoo model to consume a share-embedding table.

    The wrapper narrows the pulled block to the standard layout (each slot
    reading its shared-w plane) before the inner model applies, so every
    existing model works over a shared key space unchanged.
    """

    def __init__(self, inner, slot_share_idx, cfg: EmbeddingConfig):
        if len(slot_share_idx) == 0:
            raise ValueError("slot_share_idx must name every slot")
        idx = np.asarray(slot_share_idx, np.int32)
        if idx.min() < 0 or idx.max() >= cfg.embed_w_num:
            raise ValueError(
                f"slot_share_idx entries must be in [0, {cfg.embed_w_num})")
        self.inner = inner
        self.slot_share_idx = idx
        self.cfg = cfg
        self.emb_dim = getattr(inner, "emb_dim", None)

    def init(self, key):
        return self.inner.init(key)

    def apply(self, params, pulled, mask, dense, segment_ids, num_slots=None):
        narrowed = select_share_embedding(pulled, segment_ids,
                                          self.slot_share_idx, self.cfg)
        return self.inner.apply(params, narrowed, mask, dense, segment_ids,
                                num_slots)
