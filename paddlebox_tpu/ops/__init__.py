from paddlebox_tpu.ops.seqpool_cvm import (PooledSlots,  # noqa: F401
                                           fused_gather_seqpool_cvm,
                                           fused_seqpool_cvm,
                                           fused_seqpool_cvm_with_conv,
                                           fused_seqpool_cvm_with_pcoc)
from paddlebox_tpu.ops.cvm import cvm, cvm_inverse  # noqa: F401
from paddlebox_tpu.ops.rank_attention import rank_attention, build_rank_offset  # noqa: F401
from paddlebox_tpu.ops.batch_fc import batch_fc  # noqa: F401
from paddlebox_tpu.ops.cross_norm import (cross_norm_hadamard, data_norm,  # noqa: F401
                                          summary_update, init_summary)
from paddlebox_tpu.ops.fused_concat import fused_concat  # noqa: F401
from paddlebox_tpu.ops.extended import pull_box_extended_sparse  # noqa: F401
from paddlebox_tpu.ops.share_embedding import (  # noqa: F401
    ShareEmbeddingModel, select_share_embedding)
