"""Summary-statistics normalization ops: data_norm and cross_norm_hadamard.

Reference data_norm (operators/data_norm_op.{cc,cu}): per-column running
summary (batch_size, batch_sum, batch_square_sum);
``mean = batch_sum / batch_size``, ``scale = sqrt(batch_size /
batch_square_sum)``, ``out = (x - mean) * scale``. In multi-GPU training the
summary deltas are c_allreduce'd before applying (data_norm_op.cu
sync_stats; SURVEY.md §2.1 "CTR fused ops").

Reference cross_norm_hadamard (operators/cross_norm_hadamard.cu.h:43-95):
input is n field-pairs of embed_dim vectors (a_i, b_i) concatenated; per pair
the op emits [norm(a), norm(b), norm(a⊙b), norm(<a,b>)] — 3*embed_dim+1
columns — normalized with the same summary-stat scheme
(kernel_mean_scale: cu.h:124-129).

Both are pure functions over an explicit ``summary`` array (3, C):
row 0 = count, row 1 = sum, row 2 = square_sum — the caller owns it as a
model parameter (non-trainable, updated via `summary_update` and psum'd
across data-parallel replicas like any other stat).
"""

from __future__ import annotations

import jax.numpy as jnp


def init_summary(num_cols: int, eps: float = 1e-4) -> jnp.ndarray:
    """count=eps, sum=0, square_sum=eps: scale starts at 1, mean at 0."""
    s = jnp.zeros((3, num_cols), jnp.float32)
    s = s.at[0].set(eps)
    s = s.at[2].set(eps)
    return s


def _mean_scale(summary: jnp.ndarray):
    mean = summary[1] / summary[0]
    scale = jnp.sqrt(summary[0] / summary[2])
    return mean, scale


def data_norm(x: jnp.ndarray, summary: jnp.ndarray) -> jnp.ndarray:
    """x (B, C) normalized by running summary (3, C)."""
    mean, scale = _mean_scale(summary)
    return (x - mean) * scale


def summary_update(summary: jnp.ndarray, x: jnp.ndarray,
                   decay: float = 0.9999999,
                   axis_name=None) -> jnp.ndarray:
    """Accumulate a batch into the summary with exponential decay
    (summary_decay_rate attr, data_norm/cross_norm ops).

    axis_name: inside shard_map, psum the batch contribution across
    replicas — the reference's sync_stats c_allreduce of summary deltas
    (data_norm_op.cu multi-trainer path)."""
    from jax import lax
    b = x.shape[0]
    batch = jnp.stack([
        jnp.full((x.shape[-1],), float(b), x.dtype),
        x.sum(axis=0),
        (x * x).sum(axis=0),
    ])
    if axis_name is not None:
        batch = lax.psum(batch, axis_name)
    return summary * decay + batch


def cross_norm_hadamard(x: jnp.ndarray, summary: jnp.ndarray,
                        fields_num: int, embed_dim: int) -> jnp.ndarray:
    """x (B, 2*embed_dim*fields_num) → (B, fields_num*(3*embed_dim+1)).

    Per field-pair i with vectors a=x[:, 2i*d:(2i+1)*d], b=next d cols:
    emit [a, b, a*b, <a,b>] then summary-normalize all columns.
    """
    B = x.shape[0]
    d = embed_dim
    xr = x.reshape(B, fields_num, 2, d)
    a, b = xr[:, :, 0], xr[:, :, 1]             # (B, n, d)
    had = a * b
    dot = jnp.sum(had, axis=-1, keepdims=True)  # (B, n, 1)
    raw = jnp.concatenate([a, b, had, dot], axis=-1)   # (B, n, 3d+1)
    raw = raw.reshape(B, fields_num * (3 * d + 1))
    return data_norm(raw, summary)


def cross_norm_raw(x: jnp.ndarray, fields_num: int, embed_dim: int
                   ) -> jnp.ndarray:
    """The un-normalized [a, b, a⊙b, <a,b>] features (for summary updates)."""
    B = x.shape[0]
    d = embed_dim
    xr = x.reshape(B, fields_num, 2, d)
    a, b = xr[:, :, 0], xr[:, :, 1]
    had = a * b
    dot = jnp.sum(had, axis=-1, keepdims=True)
    return jnp.concatenate([a, b, had, dot], axis=-1).reshape(B, -1)
