"""Extended (expand) embedding pull — pull_box_extended_sparse semantics.

Reference (operators/pull_box_extended_sparse_op.{cc,cu,h}): one lookup
returns TWO tensors per slot — the base embedding `Out` and an expand
embedding `OutExtend` of a second dimension, both stored in the same
per-feature PS row ({EmbedxDim, ExpandDim} dispatch, box_wrapper.cc:444-461).
Here the table row already carries dim+expand_dim contiguous trained columns
(EmbeddingConfig.total_dim); this op is the view split, applied after the
(routed) lookup, so it fuses away under jit.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddlebox_tpu.embedding.config import EmbeddingConfig


def pull_box_extended_sparse(pulled: jnp.ndarray, cfg: EmbeddingConfig
                             ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """pulled (..., pull_width) → (base, expand (..., expand_dim)).

    Base keeps the [show, clk, w-block, embedx] layout every downstream op
    expects; expand is the trailing expand_dim columns.
    """
    if cfg.expand_dim == 0:
        raise ValueError("pull_box_extended_sparse needs expand_dim > 0")
    split = cfg.fixed_cols + cfg.dim
    return pulled[..., :split], pulled[..., split:]
