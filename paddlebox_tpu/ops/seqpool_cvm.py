"""Fused sequence-pool + CVM transform.

Reference: ``fused_seqpool_cvm`` and variants
(operators/fused/fused_seqpool_cvm_op.{cc,cu}): for every sparse slot,
sum-pool the slot's pulled embedding rows per example, then apply the CVM
(click-value-model) transform to the leading show/click columns:

- "join" phase (use_cvm=True, fused_seqpool_cvm_op.cu:166-189):
  out[0] = log(show+1); out[1] = log(click+1) - log(show+1); rest unchanged.
- "update" phase (use_cvm=False, cu:212-228): drop the cvm_offset leading
  columns.
- optional per-id filters before pooling (cu:90-163): need_filter drops ids
  with (show-click)*show_coeff + click*clk_coeff < threshold;
  embed_threshold_filter drops ids whose |embed_w| < embed_threshold once
  show > embed_threshold; quant_ratio quantizes embedx values.

The reference fuses all slots into one kernel by hand; here the whole thing
is a handful of jnp ops over the flat (B, T) token layout — one masked
multiply, one segment-sum scatter, one log transform — which XLA fuses into
the surrounding matmuls (SURVEY.md §7 design stance).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def _filter_and_quant(pulled, mask, seg_np, cvm_offset, need_filter,
                      show_coeff, clk_coeff, threshold, embed_threshold,
                      quant_ratio):
    """Shared per-id filter + quantization stage.

    cvm_offset is the column index of embed_w — 2 for the [show, clk, w]
    layout, 3 for the conv layout [show, clk, conv, w].
    """
    keep = mask
    if need_filter:
        show, clk = pulled[..., 0], pulled[..., 1]
        # threshold may be scalar, or per-slot (S,) — broadcast through
        # segment_ids to tokens (fused_seqpool_cvm_with_diff_thres,
        # operators/fused/fused_seqpool_cvm_with_diff_thres_op.cu)
        thr = jnp.asarray(threshold, jnp.float32)
        if thr.ndim == 1:
            thr = thr[seg_np]
        keep = keep & ((show - clk) * show_coeff + clk * clk_coeff >= thr)
    if embed_threshold > 0.0:
        show, w = pulled[..., 0], pulled[..., cvm_offset]
        keep = keep & ~((show > embed_threshold)
                        & (jnp.abs(w) < embed_threshold))
    x = pulled
    if quant_ratio > 0:
        # quantize embedx only (cu:143-151 quantizes past cvm_offset+1)
        q = jnp.round(x[..., cvm_offset + 1:] * quant_ratio) / quant_ratio
        x = jnp.concatenate([x[..., :cvm_offset + 1], q], axis=-1)
    return x * keep[..., None]


def _pool(x, seg_np, num_slots):
    """Sum-pool tokens into slots.

    Fast path: when every slot owns an equal contiguous run of tokens (the
    SparseLayout for uniform max_len — the common CTR geometry), pooling is
    a free reshape + axis reduction. Otherwise a constant one-hot (T, S)
    matmul — rides the MXU and avoids a scatter op (scatters carry a large
    fixed per-op cost on TPU). Measured on one v5 chip, B=8192 S=26 L=20:
    reshape-sum 19.3us vs one-hot 25.5us, and it does O(B*T*P) work instead
    of O(B*T*S*P)."""
    T = x.shape[1]
    uniform = (num_slots > 0 and T % num_slots == 0
               and np.array_equal(
                   seg_np, np.repeat(np.arange(num_slots), T // num_slots)))
    if uniform:
        B, _, P = x.shape
        return x.reshape(B, num_slots, T // num_slots, P).sum(axis=2)
    pool_mat = jnp.asarray(np.eye(num_slots, dtype=np.float32)[seg_np])
    return jnp.einsum("btp,ts->bsp", x, pool_mat)


def fused_seqpool_cvm(
    pulled: jnp.ndarray,
    mask: jnp.ndarray,
    segment_ids: np.ndarray | jnp.ndarray,
    num_slots: int,
    use_cvm: bool = True,
    cvm_offset: int = 2,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    embed_threshold: float = 0.0,
    quant_ratio: int = 0,
    flatten: bool = True,
) -> jnp.ndarray:
    """pulled (B, T, P) × mask (B, T) → pooled+CVM features.

    P = pull width: [show, clk, embed_w, embedx...]. segment_ids (T,) maps
    token columns to slots (SparseLayout.segment_ids). Returns (B, S*out_dim)
    if flatten else (B, S, out_dim), out_dim = P if use_cvm else P-cvm_offset.
    """
    B, T, P = pulled.shape
    seg_np = np.asarray(segment_ids, dtype=np.int64)
    x = _filter_and_quant(pulled, mask, seg_np, cvm_offset, need_filter,
                          show_coeff, clk_coeff, threshold, embed_threshold,
                          quant_ratio)
    pooled = _pool(x, seg_np, num_slots)
    if use_cvm:
        log_show = jnp.log(pooled[..., 0:1] + 1.0)
        log_ctr = jnp.log(pooled[..., 1:2] + 1.0) - log_show
        out = jnp.concatenate([log_show, log_ctr, pooled[..., cvm_offset:]],
                              axis=-1)
    else:
        out = pooled[..., cvm_offset:]
    if flatten:
        out = out.reshape(B, -1)
    return out


def fused_seqpool_cvm_with_pcoc(
    pulled: jnp.ndarray,
    mask: jnp.ndarray,
    segment_ids: np.ndarray | jnp.ndarray,
    num_slots: int,
    use_cvm: bool = True,
    cvm_offset: int = 7,
    max_cvm_offset: int = 7,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    quant_ratio: int = 0,
    flatten: bool = True,
) -> jnp.ndarray:
    """PCOC (predicted-click calibration) variant
    (fused_seqpool_cvm_with_pcoc_op.cu:118-258).

    Pull layout per token: [show, clk, show2, clk2, pclk_1..pclk_P, embedx]
    where P = cvm_offset - 4 (the reference's used_cvm_offset counts the
    leading show/clk/show2/clk2 plus P pclk columns; max_cvm_offset is the
    total leading width before embedx). Join-phase output per slot:

        out[0]            = log(show+1)
        out[1]            = log(clk+1)  - log(show+1)
        out[2..2+P)       = log(pclk_i+1) - log(show2+1)
        out[2+P..2+2P)    = log(pclk_i+1) - log(clk2+1)
        out[2+2P..]       = pooled embedx (passthrough)

    Update phase drops all max_cvm_offset leading columns.
    """
    B, T, E = pulled.shape
    pclk_num = cvm_offset - 4
    if pclk_num < 0:
        raise ValueError("cvm_offset must be >= 4 (show/clk/show2/clk2)")
    seg_np = np.asarray(segment_ids, dtype=np.int64)
    keep = mask
    if need_filter:
        show, clk = pulled[..., 0], pulled[..., 1]
        keep = keep & ((show - clk) * show_coeff + clk * clk_coeff
                       >= threshold)
    x = pulled
    if quant_ratio > 0:
        q = jnp.round(x[..., max_cvm_offset:] * quant_ratio) / quant_ratio
        x = jnp.concatenate([x[..., :max_cvm_offset], q], axis=-1)
    x = x * keep[..., None]
    pooled = _pool(x, seg_np, num_slots)       # (B, S, E)
    if not use_cvm:
        out = pooled[..., max_cvm_offset:]
    else:
        lg = lambda c: jnp.log(pooled[..., c:c + 1] + 1.0)
        cols = [lg(0), lg(1) - lg(0)]
        for i in range(pclk_num):
            cols.append(lg(4 + i) - lg(2))     # pclk_i vs show2
        for i in range(pclk_num):
            cols.append(lg(4 + i) - lg(3))     # pclk_i vs clk2
        cols.append(pooled[..., max_cvm_offset:])
        out = jnp.concatenate(cols, axis=-1)
    if flatten:
        out = out.reshape(B, -1)
    return out


def fused_seqpool_cvm_with_conv(
    pulled: jnp.ndarray,
    mask: jnp.ndarray,
    segment_ids: np.ndarray | jnp.ndarray,
    num_slots: int,
    use_cvm: bool = True,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    embed_threshold: float = 0.0,
    quant_ratio: int = 0,
    flatten: bool = True,
) -> jnp.ndarray:
    """Conversion-aware variant (fused_seqpool_cvm_with_conv_op.cu).

    The pull layout carries a third leading counter — conv(ersion) — after
    show/clk, so embed_w sits at column 3: [show, clk, conv, w, embedx...].
    Join phase emits [log(show+1), log(clk+1)-log(show+1),
    log(conv+1)-log(clk+1)] (the CVR chain); update phase drops all three.
    Filters/quantization run at the conv layout's column offsets.
    """
    CVM_OFFSET = 3  # embed_w column in the conv layout
    seg_np = np.asarray(segment_ids, dtype=np.int64)
    x = _filter_and_quant(pulled, mask, seg_np, CVM_OFFSET, need_filter,
                          show_coeff, clk_coeff, threshold, embed_threshold,
                          quant_ratio)
    pooled = _pool(x, seg_np, num_slots)
    if use_cvm:
        log_show = jnp.log(pooled[..., 0:1] + 1.0)
        log_ctr = jnp.log(pooled[..., 1:2] + 1.0) - log_show
        log_cvr = (jnp.log(pooled[..., 2:3] + 1.0)
                   - jnp.log(pooled[..., 1:2] + 1.0))
        out = jnp.concatenate([log_show, log_ctr, log_cvr,
                               pooled[..., CVM_OFFSET:]], axis=-1)
    else:
        out = pooled[..., CVM_OFFSET:]
    if flatten:
        out = out.reshape(out.shape[0], -1)
    return out
