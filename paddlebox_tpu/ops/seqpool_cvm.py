"""Fused sequence-pool + CVM transform.

Reference: ``fused_seqpool_cvm`` and variants
(operators/fused/fused_seqpool_cvm_op.{cc,cu}): for every sparse slot,
sum-pool the slot's pulled embedding rows per example, then apply the CVM
(click-value-model) transform to the leading show/click columns:

- "join" phase (use_cvm=True, fused_seqpool_cvm_op.cu:166-189):
  out[0] = log(show+1); out[1] = log(click+1) - log(show+1); rest unchanged.
- "update" phase (use_cvm=False, cu:212-228): drop the cvm_offset leading
  columns.
- optional per-id filters before pooling (cu:90-163): need_filter drops ids
  with (show-click)*show_coeff + click*clk_coeff < threshold;
  embed_threshold_filter drops ids whose |embed_w| < embed_threshold once
  show > embed_threshold; quant_ratio quantizes embedx values.

The reference fuses all slots into one kernel by hand; here the whole thing
is a handful of jnp ops over the flat (B, T) token layout — one masked
multiply, one segment-sum scatter, one log transform — which XLA fuses into
the surrounding matmuls (SURVEY.md §7 design stance).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def fused_seqpool_cvm(
    pulled: jnp.ndarray,
    mask: jnp.ndarray,
    segment_ids: np.ndarray | jnp.ndarray,
    num_slots: int,
    use_cvm: bool = True,
    cvm_offset: int = 2,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    embed_threshold: float = 0.0,
    quant_ratio: int = 0,
    flatten: bool = True,
) -> jnp.ndarray:
    """pulled (B, T, P) × mask (B, T) → pooled+CVM features.

    P = pull width: [show, clk, embed_w, embedx...]. segment_ids (T,) maps
    token columns to slots (SparseLayout.segment_ids). Returns (B, S*out_dim)
    if flatten else (B, S, out_dim), out_dim = P if use_cvm else P-cvm_offset.
    """
    B, T, P = pulled.shape
    keep = mask
    if need_filter:
        show, clk = pulled[..., 0], pulled[..., 1]
        keep = keep & ((show - clk) * show_coeff + clk * clk_coeff >= threshold)
    if embed_threshold > 0.0:
        show, w = pulled[..., 0], pulled[..., cvm_offset]
        keep = keep & ~((show > embed_threshold)
                        & (jnp.abs(w) < embed_threshold))
    x = pulled
    if quant_ratio > 0:
        # quantize embedx only (cu:143-151 quantizes past cvm_offset+1)
        q = jnp.round(x[..., cvm_offset + 1:] * quant_ratio) / quant_ratio
        x = jnp.concatenate([x[..., :cvm_offset + 1], q], axis=-1)
    x = x * keep[..., None]
    # pool via a constant one-hot (T, S) matmul — rides the MXU and avoids a
    # scatter op (scatters carry a large fixed per-op cost on TPU)
    seg_np = np.asarray(segment_ids, dtype=np.int64)
    pool_mat = jnp.asarray(
        np.eye(num_slots, dtype=np.float32)[seg_np])        # (T, S)
    pooled = jnp.einsum("btp,ts->bsp", x, pool_mat)
    if use_cvm:
        log_show = jnp.log(pooled[..., 0:1] + 1.0)
        log_ctr = jnp.log(pooled[..., 1:2] + 1.0) - log_show
        out = jnp.concatenate([log_show, log_ctr, pooled[..., cvm_offset:]],
                              axis=-1)
    else:
        out = pooled[..., cvm_offset:]
    if flatten:
        out = out.reshape(B, -1)
    return out
