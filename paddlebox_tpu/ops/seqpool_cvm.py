"""Fused sequence-pool + CVM transform.

Reference: ``fused_seqpool_cvm`` and variants
(operators/fused/fused_seqpool_cvm_op.{cc,cu}): for every sparse slot,
sum-pool the slot's pulled embedding rows per example, then apply the CVM
(click-value-model) transform to the leading show/click columns:

- "join" phase (use_cvm=True, fused_seqpool_cvm_op.cu:166-189):
  out[0] = log(show+1); out[1] = log(click+1) - log(show+1); rest unchanged.
- "update" phase (use_cvm=False, cu:212-228): drop the cvm_offset leading
  columns.
- optional per-id filters before pooling (cu:90-163): need_filter drops ids
  with (show-click)*show_coeff + click*clk_coeff < threshold;
  embed_threshold_filter drops ids whose |embed_w| < embed_threshold once
  show > embed_threshold; quant_ratio quantizes embedx values.

The reference fuses all slots into one kernel by hand; here the whole thing
is a handful of jnp ops over the flat (B, T) token layout — one masked
multiply, one segment-sum scatter, one log transform — which XLA fuses into
the surrounding matmuls (SURVEY.md §7 design stance).

Two fused entry points ride on top of that reference math:

- ``PooledSlots`` — a marker wrapper for input that is ALREADY pooled per
  (example, slot), produced by the fused gather-pool pull
  (``sharded.fused_pull_pool`` / ``pallas_kernels.gather_pool``). The
  ``fused_seqpool_cvm*`` functions accept it in place of the per-token
  ``pulled`` array and apply only the post-pool CVM transform — models
  stay unchanged while the (B, T, P) token matrix never materializes.
- ``fused_gather_seqpool_cvm`` — the standalone one-call form over the
  device table with a custom VJP that merges the pooled cotangent per
  unique row (dedup) before scattering into the table cotangent, so
  neither the pulled matrix nor its gradient is ever built per token.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class PooledSlots:
    """(B, S, P) per-slot sums that are ALREADY pooled.

    Produced by the fused gather-pool pull; ``fused_seqpool_cvm*`` skip
    the per-token filter/pool stages for this input and apply only the
    post-pool CVM transform. Per-token filters/quant cannot run on
    pooled sums — the fused kernel applies them pre-pool (gather_pool
    kwargs), so the model-facing call must leave them at defaults.
    """
    pooled: Any  # jnp.ndarray (B, num_slots, pull_width)

    @property
    def shape(self):
        return self.pooled.shape


def _check_pooled_kwargs(need_filter, embed_threshold, quant_ratio):
    if need_filter or embed_threshold > 0.0 or quant_ratio > 0:
        raise ValueError(
            "per-token filters/quant cannot apply to a PooledSlots input; "
            "pass them to the fused gather-pool pull "
            "(pallas_kernels.gather_pool) instead")


def _filter_and_quant(pulled, mask, seg_np, cvm_offset, need_filter,
                      show_coeff, clk_coeff, threshold, embed_threshold,
                      quant_ratio):
    """Shared per-id filter + quantization stage.

    cvm_offset is the column index of embed_w — 2 for the [show, clk, w]
    layout, 3 for the conv layout [show, clk, conv, w].
    """
    keep = mask
    if need_filter:
        show, clk = pulled[..., 0], pulled[..., 1]
        # threshold may be scalar, or per-slot (S,) — broadcast through
        # segment_ids to tokens (fused_seqpool_cvm_with_diff_thres,
        # operators/fused/fused_seqpool_cvm_with_diff_thres_op.cu)
        thr = jnp.asarray(threshold, jnp.float32)
        if thr.ndim == 1:
            thr = thr[seg_np]
        keep = keep & ((show - clk) * show_coeff + clk * clk_coeff >= thr)
    if embed_threshold > 0.0:
        show, w = pulled[..., 0], pulled[..., cvm_offset]
        keep = keep & ~((show > embed_threshold)
                        & (jnp.abs(w) < embed_threshold))
    x = pulled
    if quant_ratio > 0:
        # quantize embedx only (cu:143-151 quantizes past cvm_offset+1)
        q = jnp.round(x[..., cvm_offset + 1:] * quant_ratio) / quant_ratio
        x = jnp.concatenate([x[..., :cvm_offset + 1], q], axis=-1)
    return x * keep[..., None]


def _pool(x, seg_np, num_slots):
    """Sum-pool tokens into slots.

    Fast path: when every slot owns an equal contiguous run of tokens (the
    SparseLayout for uniform max_len — the common CTR geometry), pooling is
    a free reshape + axis reduction. Otherwise a constant one-hot (T, S)
    matmul — rides the MXU and avoids a scatter op (scatters carry a large
    fixed per-op cost on TPU). Measured on one v5 chip, B=8192 S=26 L=20:
    reshape-sum 19.3us vs one-hot 25.5us, and it does O(B*T*P) work instead
    of O(B*T*S*P)."""
    T = x.shape[1]
    uniform = (num_slots > 0 and T % num_slots == 0
               and np.array_equal(
                   seg_np, np.repeat(np.arange(num_slots), T // num_slots)))
    if uniform:
        B, _, P = x.shape
        return x.reshape(B, num_slots, T // num_slots, P).sum(axis=2)
    pool_mat = jnp.asarray(np.eye(num_slots, dtype=np.float32)[seg_np])
    return jnp.einsum("btp,ts->bsp", x, pool_mat)


def fused_seqpool_cvm(
    pulled: jnp.ndarray,
    mask: jnp.ndarray,
    segment_ids: np.ndarray | jnp.ndarray,
    num_slots: int,
    use_cvm: bool = True,
    cvm_offset: int = 2,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    embed_threshold: float = 0.0,
    quant_ratio: int = 0,
    flatten: bool = True,
) -> jnp.ndarray:
    """pulled (B, T, P) × mask (B, T) → pooled+CVM features.

    P = pull width: [show, clk, embed_w, embedx...]. segment_ids (T,) maps
    token columns to slots (SparseLayout.segment_ids). Returns (B, S*out_dim)
    if flatten else (B, S, out_dim), out_dim = P if use_cvm else P-cvm_offset.

    `pulled` may be a PooledSlots wrapper (the fused gather-pool pull):
    the per-token filter/pool stages are then already done and only the
    post-pool CVM transform applies here.
    """
    if isinstance(pulled, PooledSlots):
        _check_pooled_kwargs(need_filter, embed_threshold, quant_ratio)
        pooled = pulled.pooled
        B = pooled.shape[0]
    else:
        B, T, P = pulled.shape
        seg_np = np.asarray(segment_ids, dtype=np.int64)
        x = _filter_and_quant(pulled, mask, seg_np, cvm_offset, need_filter,
                              show_coeff, clk_coeff, threshold,
                              embed_threshold, quant_ratio)
        pooled = _pool(x, seg_np, num_slots)
    if use_cvm:
        log_show = jnp.log(pooled[..., 0:1] + 1.0)
        log_ctr = jnp.log(pooled[..., 1:2] + 1.0) - log_show
        out = jnp.concatenate([log_show, log_ctr, pooled[..., cvm_offset:]],
                              axis=-1)
    else:
        out = pooled[..., cvm_offset:]
    if flatten:
        out = out.reshape(B, -1)
    return out


def fused_seqpool_cvm_with_pcoc(
    pulled: jnp.ndarray,
    mask: jnp.ndarray,
    segment_ids: np.ndarray | jnp.ndarray,
    num_slots: int,
    use_cvm: bool = True,
    cvm_offset: int = 7,
    max_cvm_offset: int = 7,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    quant_ratio: int = 0,
    flatten: bool = True,
) -> jnp.ndarray:
    """PCOC (predicted-click calibration) variant
    (fused_seqpool_cvm_with_pcoc_op.cu:118-258).

    Pull layout per token: [show, clk, show2, clk2, pclk_1..pclk_P, embedx]
    where P = cvm_offset - 4 (the reference's used_cvm_offset counts the
    leading show/clk/show2/clk2 plus P pclk columns; max_cvm_offset is the
    total leading width before embedx). Join-phase output per slot:

        out[0]            = log(show+1)
        out[1]            = log(clk+1)  - log(show+1)
        out[2..2+P)       = log(pclk_i+1) - log(show2+1)
        out[2+P..2+2P)    = log(pclk_i+1) - log(clk2+1)
        out[2+2P..]       = pooled embedx (passthrough)

    Update phase drops all max_cvm_offset leading columns.
    """
    pclk_num = cvm_offset - 4
    if pclk_num < 0:
        raise ValueError("cvm_offset must be >= 4 (show/clk/show2/clk2)")
    if isinstance(pulled, PooledSlots):
        _check_pooled_kwargs(need_filter, 0.0, quant_ratio)
        B = pulled.shape[0]
        pooled = pulled.pooled                 # (B, S, E)
    else:
        B, T, E = pulled.shape
        seg_np = np.asarray(segment_ids, dtype=np.int64)
        keep = mask
        if need_filter:
            show, clk = pulled[..., 0], pulled[..., 1]
            keep = keep & ((show - clk) * show_coeff + clk * clk_coeff
                           >= threshold)
        x = pulled
        if quant_ratio > 0:
            q = (jnp.round(x[..., max_cvm_offset:] * quant_ratio)
                 / quant_ratio)
            x = jnp.concatenate([x[..., :max_cvm_offset], q], axis=-1)
        x = x * keep[..., None]
        pooled = _pool(x, seg_np, num_slots)   # (B, S, E)
    if not use_cvm:
        out = pooled[..., max_cvm_offset:]
    else:
        lg = lambda c: jnp.log(pooled[..., c:c + 1] + 1.0)
        cols = [lg(0), lg(1) - lg(0)]
        for i in range(pclk_num):
            cols.append(lg(4 + i) - lg(2))     # pclk_i vs show2
        for i in range(pclk_num):
            cols.append(lg(4 + i) - lg(3))     # pclk_i vs clk2
        cols.append(pooled[..., max_cvm_offset:])
        out = jnp.concatenate(cols, axis=-1)
    if flatten:
        out = out.reshape(B, -1)
    return out


def fused_seqpool_cvm_with_conv(
    pulled: jnp.ndarray,
    mask: jnp.ndarray,
    segment_ids: np.ndarray | jnp.ndarray,
    num_slots: int,
    use_cvm: bool = True,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold: float = 0.96,
    embed_threshold: float = 0.0,
    quant_ratio: int = 0,
    flatten: bool = True,
) -> jnp.ndarray:
    """Conversion-aware variant (fused_seqpool_cvm_with_conv_op.cu).

    The pull layout carries a third leading counter — conv(ersion) — after
    show/clk, so embed_w sits at column 3: [show, clk, conv, w, embedx...].
    Join phase emits [log(show+1), log(clk+1)-log(show+1),
    log(conv+1)-log(clk+1)] (the CVR chain); update phase drops all three.
    Filters/quantization run at the conv layout's column offsets.
    """
    CVM_OFFSET = 3  # embed_w column in the conv layout
    if isinstance(pulled, PooledSlots):
        _check_pooled_kwargs(need_filter, embed_threshold, quant_ratio)
        pooled = pulled.pooled
    else:
        seg_np = np.asarray(segment_ids, dtype=np.int64)
        x = _filter_and_quant(pulled, mask, seg_np, CVM_OFFSET, need_filter,
                              show_coeff, clk_coeff, threshold,
                              embed_threshold, quant_ratio)
        pooled = _pool(x, seg_np, num_slots)
    if use_cvm:
        log_show = jnp.log(pooled[..., 0:1] + 1.0)
        log_ctr = jnp.log(pooled[..., 1:2] + 1.0) - log_show
        log_cvr = (jnp.log(pooled[..., 2:3] + 1.0)
                   - jnp.log(pooled[..., 1:2] + 1.0))
        out = jnp.concatenate([log_show, log_ctr, log_cvr,
                               pooled[..., CVM_OFFSET:]], axis=-1)
    else:
        out = pooled[..., CVM_OFFSET:]
    if flatten:
        out = out.reshape(out.shape[0], -1)
    return out


# ---------------------------------------------------------------------------
# fused gather-pool form: pull + filter + pool in one op over the device
# table, with a custom VJP that merges the pooled cotangent per unique
# row before touching the table — neither the (B, T, P) pulled matrix
# nor its gradient is ever built per token. Training steps use the
# trainer's split form instead (grad taken against the pooled output,
# expanded by sharded.pooled_grad_tokens straight into the binned push);
# this one-call op is the standalone form for tests and feature
# extraction, and the reference the parity suite differentiates through.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _GPStatic:
    """Hashable static config for the gather-pool custom VJP."""
    cfg: Any                 # EmbeddingConfig (frozen dataclass)
    S: int
    L: int
    need_filter: bool
    show_coeff: float
    clk_coeff: float
    embed_threshold: float
    quant_ratio: int
    cvm_offset: int
    interpret: Any           # True = Pallas interpreter, None = backend pick


def _gp_uniform_seg(S: int, L: int) -> np.ndarray:
    return np.repeat(np.arange(S, dtype=np.int64), L)


def _gp_forward(table, idx0, thr, st: _GPStatic):
    """Pooled (B, S, P) rows: the Pallas kernel where its geometry holds
    (real TPU, or interpret=True for the CPU parity tests), else the
    identical jnp math via the unfused building blocks."""
    from paddlebox_tpu.ops import pallas_kernels as pk
    B, T = idx0.shape
    W = table.shape[1]
    use_kernel = (pk.gather_pool_geometry(B, st.S, st.L, W) is not None
                  and (st.interpret is True
                       or (st.interpret is None
                           and jax.default_backend() == "tpu")))
    if use_kernel:
        return pk.gather_pool(
            table, idx0, st.cfg, st.S, st.L, need_filter=st.need_filter,
            show_coeff=st.show_coeff, clk_coeff=st.clk_coeff, threshold=thr,
            embed_threshold=st.embed_threshold, quant_ratio=st.quant_ratio,
            cvm_offset=st.cvm_offset, interpret=st.interpret)
    P = st.cfg.pull_width
    seg = _gp_uniform_seg(st.S, st.L)
    pulled = jnp.take(table, idx0.reshape(-1), axis=0)[:, :P].reshape(
        B, T, P)
    x = _filter_and_quant(pulled, jnp.ones((B, T), bool), seg,
                          st.cvm_offset, st.need_filter, st.show_coeff,
                          st.clk_coeff, thr, st.embed_threshold,
                          st.quant_ratio)
    return _pool(x, seg, st.S)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _gather_pool_vjp(table, idx0, mask, thr, st: _GPStatic):
    return _gp_forward(table, idx0, thr, st)


def _gather_pool_vjp_fwd(table, idx0, mask, thr, st: _GPStatic):
    return _gp_forward(table, idx0, thr, st), (table, idx0, mask, thr)


def _gather_pool_vjp_bwd(st: _GPStatic, res, d_pooled):
    """Pooling is linear, so each token's cotangent is its (example,
    slot) pooled row times the per-token keep factor; duplicates merge
    per unique row (dedup_tokens — the 852k-token → ~330k-unique case)
    before the one scatter into the table cotangent. Quantization is
    straight-through (the reference CUDA grad op distributes gradients
    without re-applying the rounding; jnp.round's a.e.-zero derivative
    would silently kill embedx grads)."""
    from paddlebox_tpu.embedding.sharded import dedup_tokens
    table, idx0, mask, thr = res
    B, T = idx0.shape
    S, P = st.S, st.cfg.pull_width
    seg = _gp_uniform_seg(S, st.L)
    bs = (jnp.arange(B, dtype=jnp.int32)[:, None]
          * S + jnp.asarray(seg, jnp.int32)[None, :]).reshape(-1)
    d_tok = jnp.take(d_pooled.reshape(B * S, P), bs, axis=0)
    keep = mask.reshape(-1)
    if st.need_filter or st.embed_threshold > 0.0:
        rows = jnp.take(table, idx0.reshape(-1), axis=0)
        show, clk = rows[:, 0], rows[:, 1]
        if st.need_filter:
            t = jnp.asarray(thr, jnp.float32)
            t_tok = t[jnp.asarray(seg)] if t.ndim == 1 else t
            t_flat = jnp.broadcast_to(t_tok, (B, T)).reshape(-1)
            keep = keep & ((show - clk) * st.show_coeff
                           + clk * st.clk_coeff >= t_flat)
        if st.embed_threshold > 0.0:
            w = rows[:, st.cvm_offset]
            keep = keep & ~((show > st.embed_threshold)
                            & (jnp.abs(w) < st.embed_threshold))
    d_tok = d_tok * keep.astype(d_tok.dtype)[:, None]
    uniq, inverse = dedup_tokens(idx0.reshape(-1))
    merged = jnp.zeros((uniq.shape[0], P),
                       d_tok.dtype).at[inverse].add(d_tok)
    pad = jnp.zeros((merged.shape[0], table.shape[1] - P), merged.dtype)
    d_table = jnp.zeros_like(table).at[uniq].add(
        jnp.concatenate([merged, pad], axis=1))
    f0 = jax.dtypes.float0
    return (d_table, np.zeros(idx0.shape, f0), np.zeros(mask.shape, f0),
            jnp.zeros_like(thr))


_gather_pool_vjp.defvjp(_gather_pool_vjp_fwd, _gather_pool_vjp_bwd)


def fused_gather_seqpool_cvm(
    table: jnp.ndarray,
    idx: jnp.ndarray,
    mask: jnp.ndarray,
    segment_ids: np.ndarray | jnp.ndarray,
    num_slots: int,
    cfg,
    use_cvm: bool = True,
    cvm_offset: int = 2,
    need_filter: bool = False,
    show_coeff: float = 0.2,
    clk_coeff: float = 1.0,
    threshold=0.96,
    embed_threshold: float = 0.0,
    quant_ratio: int = 0,
    flatten: bool = True,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """table (n_rows, W) × idx/mask (B, T) → pooled+CVM features, fused.

    Same contract as ``fused_seqpool_cvm(lookup(table, idx), mask, ...)``
    for f32 tables whose row NULL_INDEX is the all-zero row, but the
    per-token pulled matrix never materializes: the forward gathers and
    pools inside one Pallas kernel (or the equivalent jnp reference off
    the kernel's geometry), and the custom VJP merges the pooled
    cotangent per unique row before the single table scatter. Requires
    the uniform slot layout (equal max_len per slot). cfg is the table's
    EmbeddingConfig (pull_width source of truth).
    """
    if cfg.mf_create_threshold > 0 or cfg.expand_create_threshold > 0:
        # both the kernel and the jnp path here gather raw rows —
        # lookup()'s gate_pull presence masks would be silently skipped
        raise ValueError(
            "fused_gather_seqpool_cvm skips gate_pull; create-threshold "
            "configs (mf/expand_create_threshold > 0) must use the "
            "unfused lookup + fused_seqpool_cvm path")
    seg_np = np.asarray(segment_ids, dtype=np.int64)
    S = num_slots
    if S <= 0 or idx.shape[1] % S:
        raise ValueError(f"token axis {idx.shape[1]} must be a multiple "
                         f"of num_slots {S}")
    L = idx.shape[1] // S
    if not np.array_equal(seg_np, _gp_uniform_seg(S, L)):
        raise ValueError(
            "fused gather-pool requires the uniform slot layout "
            "(equal max_len per slot); use the unfused path")
    mask_a = jnp.asarray(mask)
    idx0 = jnp.where(mask_a, jnp.asarray(idx), 0).astype(jnp.int32)
    st = _GPStatic(cfg=cfg, S=S, L=L, need_filter=bool(need_filter),
                   show_coeff=float(show_coeff),
                   clk_coeff=float(clk_coeff),
                   embed_threshold=float(embed_threshold),
                   quant_ratio=int(quant_ratio),
                   cvm_offset=int(cvm_offset), interpret=interpret)
    thr = jnp.asarray(threshold, jnp.float32)
    pooled = _gather_pool_vjp(table, idx0, mask_a, thr, st)
    return fused_seqpool_cvm(PooledSlots(pooled), mask, segment_ids,
                             num_slots, use_cvm=use_cvm,
                             cvm_offset=cvm_offset, flatten=flatten)
