"""Rank attention — page-view cross-ad attention.

Reference: ``rank_attention`` op (operators/rank_attention_op.cc,
rank_attention.cu.h:27-115): each example (an ad impression) attends over the
other ads in the same page view (PV). A ``rank_offset`` int matrix
(B, 2*max_rank+1) encodes, per example: col 0 = its own rank (1-based, 0 =
invalid); for k in [0, max_rank): col 2k+1 = rank of the k-th PV peer (0 =
absent), col 2k+2 = that peer's row index in the batch. A learnable
``rank_param`` of shape (max_rank*max_rank*in_dim, out_dim) holds one
(in_dim, out_dim) block per (own_rank, peer_rank) pair.

The CUDA implementation materializes expanded input/param helper tensors and
runs a batched GEMM; here it is one gather + one einsum that XLA maps
straight onto the MXU.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def rank_attention(x: jnp.ndarray, rank_offset: jnp.ndarray,
                   rank_param: jnp.ndarray, max_rank: int) -> jnp.ndarray:
    """x (B, in_dim), rank_offset (B, 2*max_rank+1) int32,
    rank_param (max_rank*max_rank*in_dim, out_dim) → (B, out_dim)."""
    B, in_dim = x.shape
    out_dim = rank_param.shape[1]
    ins_rank = rank_offset[:, 0]                     # (B,)
    peer_rank = rank_offset[:, 1::2]                 # (B, K)
    peer_idx = rank_offset[:, 2::2]                  # (B, K)
    valid = (ins_rank > 0)[:, None] & (peer_rank > 0)
    xg = x[jnp.clip(peer_idx, 0, B - 1)]             # (B, K, in_dim)
    xg = jnp.where(valid[..., None], xg, 0.0)
    blk = (ins_rank[:, None] - 1) * max_rank + (peer_rank - 1)
    blk = jnp.clip(blk, 0, max_rank * max_rank - 1)  # (B, K)
    params = rank_param.reshape(max_rank * max_rank, in_dim, out_dim)
    pb = params[blk]                                 # (B, K, in_dim, out_dim)
    # xg is already zeroed at invalid positions, so invalid einsum terms
    # vanish without masking pb too
    return jnp.einsum("bki,bkio->bo", xg, pb)


def build_rank_offset(ranks: np.ndarray, pv_groups: np.ndarray,
                      max_rank: int) -> np.ndarray:
    """Host-side construction of the rank_offset matrix from per-example
    rank + PV group ids (the GetRankOffset[GPU] path of
    SlotPaddleBoxDataFeed, data_feed.cu:208 CopyRankOffsetKernel).

    ranks     : (B,) int 1-based ad rank within its PV (0 = invalid)
    pv_groups : (B,) int group id, equal for examples of the same PV
    Returns (B, 2*max_rank+1) int32. Vectorized — this runs on the
    per-batch pack hot path (PVRankModel.batch_extras); when several
    members of a PV share a rank, the last (highest index) wins, like
    the reference kernel's last-writer scatter.
    """
    ranks = np.asarray(ranks)
    pv_groups = np.asarray(pv_groups)
    B = len(ranks)
    out = np.zeros((B, 2 * max_rank + 1), dtype=np.int32)
    out[:, 0] = ranks
    if B == 0:
        return out
    sel = np.flatnonzero((ranks >= 1) & (ranks <= max_rank))
    if len(sel):
        # last member per (group, rank): lexsort by (group, rank, idx)
        order = np.lexsort((sel, ranks[sel], pv_groups[sel]))
        s = sel[order]
        gg, rr = pv_groups[s], ranks[s]
        is_last = np.ones(len(s), bool)
        is_last[:-1] = (gg[1:] != gg[:-1]) | (rr[1:] != rr[:-1])
        lg, lr, lj = gg[is_last], rr[is_last], s[is_last]
        ug, gpos = np.unique(lg, return_inverse=True)
        peer_r = np.zeros((len(ug), max_rank), np.int32)
        peer_j = np.zeros((len(ug), max_rank), np.int32)
        peer_r[gpos, lr - 1] = lr
        peer_j[gpos, lr - 1] = lj
        gi = np.searchsorted(ug, pv_groups)
        gi_c = np.minimum(gi, len(ug) - 1)
        want = (ranks > 0) & (ug[gi_c] == pv_groups)
        out[:, 1::2] = np.where(want[:, None], peer_r[gi_c], 0)
        out[:, 2::2] = np.where(want[:, None], peer_j[gi_c], 0)
    return out


def build_rank_offset_reference(ranks: np.ndarray, pv_groups: np.ndarray,
                                max_rank: int) -> np.ndarray:
    """Straightforward per-member loop — ground truth for the vectorized
    builder's tests (mirrors CopyRankOffsetKernel literally)."""
    B = len(ranks)
    out = np.zeros((B, 2 * max_rank + 1), dtype=np.int32)
    out[:, 0] = ranks
    by_group: dict[int, list[int]] = {}
    for i, g in enumerate(np.asarray(pv_groups).tolist()):
        by_group.setdefault(g, []).append(i)
    for g, members in by_group.items():
        for i in members:
            if ranks[i] <= 0:
                continue
            for j in members:
                r = int(ranks[j])
                if 1 <= r <= max_rank:
                    out[i, 2 * (r - 1) + 1] = r
                    out[i, 2 * (r - 1) + 2] = j
    return out
