"""Pallas TPU kernels for the embedding-table hot path.

Four kernel families live here:

- ``gather_pool`` (the fused pull for multi-hot/wide layouts): gathers
  rows from the HBM device table and sum-pools them per (example, slot)
  segment in VMEM, so the (tokens, pull_width) pulled matrix never
  materializes — the pull-side dual of ``binned_push`` (see its section
  comment for the rationale and measurements).

- ``scatter_accumulate`` (the fused push for premerged unique lanes):
  the mirror image of ``gather_pool`` — DMA-gathers exactly the table
  rows the premerged cotangent lanes touch, applies the optimizer
  row-wise in VMEM, and DMA-writes each row back once. Neither the
  (tokens, pull_width) cotangent matrix nor the (n_rows, grad_width+3)
  full-table accumulator ever materializes, and the O(table) update
  pass of the scatter/binned engines disappears (see its section
  comment). Engine selection across the three push engines is owned by
  ``resolve_push_engine`` — ONE resolver shared by the compiled
  dispatch and the per-point bench record.

- ``binned_push`` (the production path, flags.binned_push): replaces the
  XLA token scatter-add with block-binned one-hot MXU matmuls that build
  a per-row merge accumulator; the optimizer then applies as ONE fused
  XLA pass over the table — see the section comment. This is the single
  largest perf lever in the framework (train step 15.2ms -> 8.0ms on one
  v5e at batch 8192 across rounds 2-3, 546k -> 1.02M examples/sec/chip;
  the round-3 move of the optimizer OUT of the kernel bought 11.1 ->
  8.0ms alone).
- ``merge_update`` (kept for experiments, default off): fuses only the
  table-update scan after XLA's scatter has built the accumulator.

Gated by ``PBTPU_PALLAS`` (default: on for TPU, off elsewhere).
Measured on one v5e chip, 1M x 13 f32 table, 20% rows touched, adagrad:
XLA path 25.3us, this kernel 19.1us at block_rows=512 (-25%). Narrow rows
pad to 128 lanes in VMEM, so keep block_rows modest: 4096-row blocks of a
13-wide table already blow the 16MB VMEM budget. The kernel reuses
``embedding.optim.apply_updates`` verbatim inside the kernel body, so
numerics are bit-identical to the XLA path and every optimizer
(sgd/adagrad/adam/ftrl) works unchanged.

On CPU the kernel runs in interpret mode — the pure-Python Pallas
interpreter — which is how the tests exercise it without TPU hardware
(SURVEY.md §4: everything must be testable hardware-free).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddlebox_tpu.embedding.config import EmbeddingConfig
from paddlebox_tpu.embedding.optim import apply_updates
from paddlebox_tpu.jax_compat import shape_struct


def use_pallas() -> bool:
    """Default OFF. The round-1 "+16% end-to-end win" was an artifact of
    timing windows terminated by block_until_ready, which returns early
    over the axon tunnel; with windows terminated by a real device_get,
    the XLA scatter+select path is ~15% FASTER than this kernel (14.9ms vs
    17.5ms DeepFM step, batch 8192, 512k-key working set, one v5e), and
    the kernel's {1,0} operand layout constraint forces padded O(table)
    copies that OOM multi-GB working sets (measured: 3x 5GB copies at
    10.5M x 21 f32). PBTPU_PALLAS=1 re-enables for experiments.

    Read at TRACE time: set it before the first train step compiles.
    Flipping it later does nothing — jitted steps (donated, fed back) never
    retrace, so the already-compiled path keeps running."""
    return os.environ.get("PBTPU_PALLAS") == "1"


def _merge_update_kernel(table_ref, acc_ref, out_ref, *, cfg: EmbeddingConfig):
    rows = table_ref[...]
    acc = acc_ref[...]
    gw = cfg.grad_width
    new_rows = apply_updates(rows, acc[:, :gw], acc[:, gw], acc[:, gw + 1],
                             cfg)
    touched = acc[:, gw + 2] > 0
    out_ref[...] = jnp.where(touched[:, None], new_rows, rows)


@functools.partial(jax.jit, static_argnames=("cfg", "block_rows", "interpret"))
def merge_update(table: jnp.ndarray, acc: jnp.ndarray, cfg: EmbeddingConfig,
                 block_rows: int = 512,
                 interpret: bool | None = None) -> jnp.ndarray:
    """One fused pass of the per-step table update.

    table : (N, row_width) f32
    acc   : (N, grad_width + 3) f32 — summed [grads, show, clk, touch_count]
            per row (the output of the scatter-add merge)
    Returns the updated table; identical to the jnp path in sharded.push.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, w = table.shape
    a = acc.shape[1]
    grid = (pl.cdiv(n, block_rows),)
    # inside shard_map the output varies over the same mesh axes as the
    # table shard (new-style shard_map vma checking)
    vma = getattr(jax.typeof(table), "vma", frozenset())
    if interpret and vma:
        # The Pallas interpreter evaluates the kernel jaxpr with
        # vma-carrying block values, and EVERY op mixing a literal
        # (x * 2.0, x > 0, ...) trips shard_map's vma check — interpret
        # mode fundamentally cannot run nontrivial kernels inside a
        # check_vma shard_map (JAX 0.9.0). Use the identical jnp math on
        # CPU test meshes; Mosaic lowering on real TPU is a custom call
        # and does not hit this.
        gw = cfg.grad_width
        new_rows = apply_updates(table, acc[:, :gw], acc[:, gw],
                                 acc[:, gw + 1], cfg)
        return jnp.where((acc[:, gw + 2] > 0)[:, None], new_rows, table)
    return pl.pallas_call(
        functools.partial(_merge_update_kernel, cfg=cfg),
        out_shape=shape_struct((n, w), table.dtype, vma=vma),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, w), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, a), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, w), lambda i: (i, 0)),
        interpret=interpret,
    )(table, acc)


# ---------------------------------------------------------------------------
# Binned push: the scatter-free merge-update.
#
# XLA's scatter is random-access latency-bound INSIDE the fused step
# (in-step A/B on one v5e, 213k tokens: the scatter step runs 15.5ms vs
# 7.7ms with this kernel at dim 8 — isolated scatter microbenchmarks
# read 100x faster and are a trap; only in-step A/B is decision-grade).
# This kernel replaces it with MXU matmuls: tokens are sorted by row
# id (one argsort), bucketed to contiguous table "super-blocks", and each
# super-block's accumulator is built as one-hot(local_row) @ payload — a
# streaming matmul instead of random-access writes. The optimizer then
# applies OUTSIDE the kernel as one fused full-width XLA pass (the merge +
# update halves of PushMergeCopy, box_wrapper.cu:630-830; see
# _binned_acc_kernel's docstring for why the split wins on TPU).
#
# Exactness: the payload crosses the MXU as an n_split-plane bf16 mantissa
# split computed IN-KERNEL (hi/mid/lo by integer masking, so
# --xla_allow_excess_precision cannot elide the rounding); one-hot entries
# are exact in bf16 and accumulation is f32, so n_split=3 matches the f32
# scatter to ~1e-7 relative (measured 1.6e-7 over a 213k-token batch;
# summation ORDER differs from XLA's scatter, so bitwise equality is not
# expected). n_split=1 rounds grads to bf16 (2x fewer dots).
#
# Packed operand: [payload_f32 (PP lanes) | id_hi | id_lo], PP = payload
# padded to a multiple of 8. Because the mantissa split happens in VMEM,
# the operand width is independent of n_split — ~5x less HBM/DMA traffic
# than the old pre-split 128-lane layout for narrow CTR payloads, and NO
# upper width limit: wide rows (dim 64..280+, the reference's full embedx
# envelope, box_wrapper.cc:444-461) run the same kernel with a >128-lane
# accumulator that Mosaic tiles across lane registers.
#
# Lane packing (narrow rows): G = pow2(128 // PP) row-groups share one
# dot's 128 output lanes (each token's payload is routed into its group's
# lane block), so narrow CTR payloads do not waste ~10x MXU throughput on
# lane padding. Wide rows (PP > 64) take G = 1 and the dot's output lanes
# are the payload itself.
#
# Measured (one v5e, 528k x 13 f32 table, 213k tokens, adagrad, forced-D2H
# repeat-in-one-jit windows): XLA scatter+update ~16.6 ms/call; round-2
# kernel (in-VMEM optimizer) 5.2 ms; round-3 pre-split acc-only 3.6 ms;
# this in-kernel-split layout is measured by bench.py's stage attribution
# (sparse_push) and the dim-64/128 matrix points.
# ---------------------------------------------------------------------------

_BP_TILE = 1024          # tokens per DMA/matmul tile
_BP_MAX_PP = 512         # accumulator lane cap (dim 280 -> PP 288)


def _bp_lanes(cfg: EmbeddingConfig, rows: int):
    """Shared lane geometry: (P, PP, G, target_SB) or None past the
    width cap. The single source of truth for both the kernel geometry
    and the working-set row alignment — they MUST agree or shard row
    counts desynchronize from the kernel's actual block choice.

    G = largest power of two <= 128 // PP: lane routing only needs
    G * PP <= 128, and a non-pow2 G (PP=24 -> 128//24=5) would fail the
    SB % G divisibility and silently lose the kernel for those widths.
    PP > 64 -> G=1: the dot's output lanes are the payload itself
    (Mosaic tiles >128-lane accumulators across lane registers).

    target_SB trades one-hot dot FLOPs against grid overhead: each
    token's one-hot row is RB = SB/G wide (work ~ tokens * RB * PP per
    plane) while each block costs a fixed ~20us of DMA/prologue (cost ~
    n_rows/SB) — so SB* ~ sqrt(c * n_rows * 128/PP), c fitted on v5e
    (~3; for PP <= 64 the 128/PP ratio equals G up to pow2 rounding, so
    this reduces to the round-3 sqrt(3*G*n_rows)). A 10.5M-row table at
    SB=4096 is 2560 mostly-empty grid steps (measured +2.6ms); the
    bench's 557k-row table at SB=16384 wastes 4x MXU work (measured
    +1.4ms)."""
    P = cfg.grad_width + 3
    PP = -(-P // 8) * 8
    if PP > _BP_MAX_PP:
        return None
    G = max(1, 1 << ((128 // PP).bit_length() - 1)) if PP <= 128 else 1
    target = int((3.0 * max(1, rows) * 128.0 / PP) ** 0.5)
    return P, PP, G, target


def _bp_geometry(cfg: EmbeddingConfig, n_rows: int):
    """(payload P, padded PP, groups G, super-block SB) or None if the
    table doesn't fit the kernel's divisibility/width needs."""
    lanes = _bp_lanes(cfg, n_rows)
    if lanes is None:
        return None
    P, PP, G, target = lanes
    # nearest dividing block to target_SB. RB = SB/G is capped at 2048:
    # the (TILE, RB) one-hot operand blew v5e's 16MB scoped-vmem limit
    # at RB=4096 (the tile also halves past RB 1024 — _bp_tile).
    best = None
    SB = min(2048 * G, 1 << 16)
    while SB >= 512:
        if n_rows % SB == 0 and SB % G == 0:
            if best is None or abs(SB - target) < abs(best - target):
                best = SB
        SB //= 2
    if best is None:
        return None
    return P, PP, G, best


def bp_row_alignment(cfg: EmbeddingConfig, rows: int) -> int:
    """Row-count alignment that lets `_bp_geometry` pick its TARGET
    super-block for a table of ~`rows` rows: the power of two nearest
    target_SB, clamped to [4096, RB-cap]. Working-set builders align
    shard row counts to this — big tables get big-block divisibility,
    small tables keep the cheap 4096 alignment."""
    lanes = _bp_lanes(cfg, rows)
    if lanes is None:
        return 4096
    _, _, G, target = lanes
    pow2 = 1 << max(0, target.bit_length() - 1)
    if target - pow2 > 2 * pow2 - target:       # round to nearest pow2
        pow2 <<= 1
    return max(4096, min(pow2, 2048 * G, 1 << 16))


def _bp_tile(SB: int, G: int) -> int:
    """Tokens per DMA/matmul tile: halved for big blocks so the
    (TILE, RB) one-hot operand stays ~2MB."""
    return _BP_TILE if SB // G <= 1024 else _BP_TILE // 2


def _bp_acc_width(G: int, PP: int) -> int:
    """Accumulator lane count: G*PP for narrow rows; padded to a full
    128-lane tile past one tile (Mosaic rejects multi-tile shapes with
    odd tails, and a 136-lane dot already costs two 128-lane MXU blocks,
    so the padding is free)."""
    gp = G * PP
    return gp if gp <= 128 else -(-gp // 128) * 128


def _binned_acc_kernel(rstart_ref, end_ref, packed_ref, acc_ref,
                       pack_s, sem, *, PP: int, G: int, SB: int,
                       n_split: int, TILE: int):
    """Per-block merge accumulator via one-hot MXU matmuls.

    Writes this block's accumulator in GROUPED layout (RB, G*PP) — row
    ``local % RB``, lane block ``(local // RB) * PP`` — which the caller
    untangles with a reshape/transpose that XLA fuses into the table
    update. The optimizer deliberately does NOT run in here: a
    (block, group)-tiled elementwise chain wastes ~90% of each VPU lane
    on narrow CTR rows, while the same update as ONE fused XLA pass over
    the whole table runs at full width (measured on one v5e, 528k x 13
    adagrad: in-kernel update ~3.5ms of the old 5.2ms kernel vs 0.5ms as
    a fused XLA pass over the grouped acc).

    The bf16 mantissa planes are built HERE from the f32 payload (cheap
    VPU integer masking on the tile) rather than pre-split host/XLA-side:
    the packed operand carries each payload value once, so DMA traffic is
    ~(PP+2)/128 of the old pre-split layout and the payload-prep XLA
    chain disappears from the step."""
    RB = SB // G
    b = pl.program_id(0)
    start = rstart_ref[b]
    endv = end_ref[b]
    acc_ref[...] = jnp.zeros_like(acc_ref)
    n_t = lax.div(endv - start + TILE - 1, TILE)

    def _copy(t):
        slot = lax.rem(t, 2)
        # rstart entries are //8*8-aligned by construction (plan builder
        # and device fallback both); Mosaic needs the hint to prove the
        # row slice respects (8,128) memref tiling for W > 128 operands
        row0 = pl.multiple_of(start + t * TILE, 8)
        return pltpu.make_async_copy(
            packed_ref.at[pl.ds(row0, TILE), :],
            pack_s.at[slot], sem.at[slot])

    # double-buffered DMA: tile t+1 streams in while tile t computes
    @pl.when(n_t > 0)
    def _prefetch_first():
        _copy(0).start()

    def body(t, _):
        @pl.when((t + 1) < n_t)
        def _prefetch_next():
            _copy(t + 1).start()

        _copy(t).wait()
        packed = pack_s[lax.rem(t, 2)]
        off = start + t * TILE
        # row id rides the two lanes PAST the payload as two exact
        # integer-valued floats (hi*4096+lo): f32 BIT patterns of small
        # ints are denormals and XLA flushes them, so a bitcast column
        # would read back as zeros
        tok = (packed[:, PP:PP + 1].astype(jnp.int32) * 4096
               + packed[:, PP + 1:PP + 2].astype(jnp.int32))
        pos = lax.broadcasted_iota(jnp.int32, (TILE, 1), 0) + off
        local = tok - b * SB
        valid = (pos < endv) & (local >= 0) & (local < SB)
        grp = jnp.where(valid, local // RB, G)
        within = jnp.where(valid, local % RB, RB)
        oh = (within == lax.broadcasted_iota(
            jnp.int32, (TILE, RB), 1)).astype(jnp.bfloat16)
        AW = _bp_acc_width(G, PP)
        lane_grp = lax.broadcasted_iota(jnp.int32, (TILE, AW), 1) // PP
        # in-kernel mantissa split: plane s holds the top 16 bits of the
        # running residual (exact in bf16); the LAST plane is the raw
        # residual, which after two maskings has <= 8 significant bits
        # (exact) and for n_split=1 is the full payload (bf16-rounded).
        # Wide rows (G=1, AW > PP) split the packed tile whole — the id /
        # padding lanes past PP are split along for the ride; their acc
        # lanes are never read by the caller's [:, :P] slice.
        rem = packed[:, 0:PP] if G > 1 else packed[:, 0:AW]
        for s in range(n_split):
            if s == n_split - 1:
                plane = rem
            else:
                plane = lax.bitcast_convert_type(
                    lax.bitcast_convert_type(rem, jnp.int32)
                    & jnp.int32(-65536), jnp.float32)
                rem = rem - plane
            wide = jnp.tile(plane, (1, G)) if G > 1 else plane
            routed = jnp.where(lane_grp == grp, wide, 0.0)
            acc_ref[...] += lax.dot_general(
                oh, routed.astype(jnp.bfloat16), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return 0

    lax.fori_loop(0, n_t, body, 0)


# ---------------------------------------------------------------------------
# _bp_pack width-class engines.
#
# The pack's one expensive op is the token reorder (``[order]`` row
# gather), and the v5e row-gather sweep is sharply non-monotone in source
# width: <=13-lane sources gather at ~5-10ns/row (fast narrow path),
# 14..63-lane sources fall off a cliff (3-8x slower per row — 23.2ms at
# 40 lanes vs 3.6ms at 128 over 852k tokens), and >=64-lane sources are
# back on the fast path. One pack layout therefore cannot serve every
# payload width: the round-5 _bp_pack rewrite moved the dim-8 headline's
# 12-lane payload onto the pad-first layout and silently halved headline
# throughput (VERDICT r5 — reverting that one function restored 1.87x).
# The engines below make the choice EXPLICIT, per width class, overridable
# for in-composed-step A/Bs (flags.pack_engine) and recorded per bench
# matrix point (pack_engine()) so a wrong choice alarms instead of
# shipping:
#
#   narrow      (P < 14)       reorder at the logical payload width, pad
#                              after — the fast-narrow-gather path.
#   gather_zone (14 <= P < 64) pad to 64 lanes BEFORE the reorder (the
#                              smallest fast-path width), zero-extend to
#                              the DMA width after — half the gather
#                              bytes of the 128-lane layout.
#   wide        (P >= 64)      pack at the full 128-lane-tile DMA width
#                              first, one wide gather.
# ---------------------------------------------------------------------------

PACK_ENGINES = ("narrow", "gather_zone", "wide")


def pack_width_class(P: int) -> str:
    """Width class of a P-lane push payload (the v5e gather-sweep zones;
    the 14-lane zone start matches device_width's pad rule)."""
    if P < 14:
        return "narrow"
    if P < 64:
        return "gather_zone"
    return "wide"


def _resolve_pack_engine(P: int, premerged: bool) -> str:
    """THE pack-engine resolver — both the compiled path (_bp_pack) and
    the per-point bench record (pack_engine) call this one function, so
    the record can never name a code path the program does not contain
    (the round-5 unattributable-regression failure mode). Raises on a
    typo'd forced engine: the flag exists for trustworthy A/Bs."""
    if premerged:
        # premerged lanes arrive sorted (order=None): no reorder
        # compiles regardless of width class or override
        return "premerged_no_reorder"
    from paddlebox_tpu.config import flags as config_flags
    eng = config_flags.pack_engine
    if eng in PACK_ENGINES:
        return eng
    if eng != "auto":
        raise ValueError(f"pack_engine={eng!r} (want 'auto' or one of "
                         f"{PACK_ENGINES})")
    return pack_width_class(P)


def pack_engine(cfg: EmbeddingConfig, n_rows: int,
                premerged: bool = False) -> str | None:
    """Which _bp_pack code path the binned push compiles with for this
    (cfg, rows) — "narrow" | "gather_zone" | "wide", or None when the
    binned kernel does not engage (scatter-engine dispatch has no pack).
    flags.pack_engine overrides for A/B runs. Recorded per bench matrix
    point, so every engine choice stays measured round over round.

    premerged: the dedup premerge feeds the pack already-sorted lanes
    (order=None), so NO reorder compiles regardless of width class —
    reported as "premerged_no_reorder" so the per-point record names the
    code path the program actually contains, not the one the width alone
    would pick."""
    if binned_push_geometry(cfg, n_rows) is None:
        return None
    return _resolve_pack_engine(cfg.grad_width + 3, premerged)


def _pack_narrow(grads, shows, clks, hi, lo, order, tok, P, PP, W):
    # reorder at the logical payload width (fast <14-lane gathers), pad
    # to the DMA width after — one extra elementwise pass over the
    # already-sorted payload
    payload = jnp.concatenate(
        [grads, shows[:, None], clks[:, None],
         jnp.ones((tok, 1), jnp.float32)], axis=1)
    s_pay = jnp.take(payload, order, axis=0)
    return jnp.concatenate(
        [s_pay, jnp.zeros((tok, PP - P), jnp.float32),
         jnp.take(hi, order)[:, None], jnp.take(lo, order)[:, None],
         jnp.zeros((tok, W - PP - 2), jnp.float32)], axis=1)


def _pack_gather_zone(grads, shows, clks, hi, lo, order, tok, P, PP, W):
    # 14..63-lane gathers are the pathological zone — pad to 64 lanes
    # (the smallest fast-path source width) BEFORE the reorder, then
    # zero-extend to the DMA width; the gather moves half the bytes of
    # the 128-lane-first layout
    G64 = 64 if PP + 2 <= 64 else W
    pay64 = jnp.concatenate(
        [grads, shows[:, None], clks[:, None],
         jnp.ones((tok, 1), jnp.float32),
         jnp.zeros((tok, PP - P), jnp.float32),
         hi[:, None], lo[:, None],
         jnp.zeros((tok, G64 - PP - 2), jnp.float32)], axis=1)
    s64 = jnp.take(pay64, order, axis=0)
    if G64 == W:
        return s64
    return jnp.concatenate(
        [s64, jnp.zeros((tok, W - G64), jnp.float32)], axis=1)


def _pack_wide(grads, shows, clks, hi, lo, order, tok, P, PP, W):
    # >=64-lane payloads are already on the fast gather path — pack at
    # the full DMA width first, one wide gather (order=None skips the
    # gather entirely: pre-merged lanes arrive sorted)
    pay_full = jnp.concatenate(
        [grads, shows[:, None], clks[:, None],
         jnp.ones((tok, 1), jnp.float32),
         jnp.zeros((tok, PP - P), jnp.float32),
         hi[:, None], lo[:, None],
         jnp.zeros((tok, W - PP - 2), jnp.float32)], axis=1)
    if order is None:
        return pay_full
    return jnp.take(pay_full, order, axis=0)


_PACK_BUILDERS = {"narrow": _pack_narrow, "gather_zone": _pack_gather_zone,
                  "wide": _pack_wide}


def _bp_pack(idx, grads, shows, clks, geom, TILE: int, n_rows: int,
             plan=None):
    """Build the kernel's packed operand: tokens grouped by super-block,
    each row ``[payload_f32 (PP lanes) | id_hi | id_lo]`` padded to a
    multiple of 8 lanes (then to whole 128-lane tiles for the DMA).
    Split out so bench.py's stage attribution can time the prep
    separately from the kernel.

    The token reorder is dispatched per payload width class (see the
    section comment above): narrow payloads gather at logical width and
    pad after; gather-zone widths pad to 64 lanes first; wide payloads
    pack at the full DMA width. All three produce the identical packed
    array — only the gather's source width differs — so forcing one via
    flags.pack_engine is always legal (the A/B knob)."""
    P, PP, G, SB = geom
    NB = n_rows // SB
    tok = idx.shape[0]
    # Mosaic DMA slices must be 128-lane aligned (memref tiling (1,128));
    # narrow payloads pad up to one lane tile, wide ones to the next
    W = -(-(PP + 2) // 128) * 128
    order = rstart = end = None
    if plan is None:
        order = jnp.argsort(idx)
        s_idx = idx[order]
        bounds = jnp.searchsorted(
            s_idx,
            jnp.arange(NB + 1, dtype=jnp.int32) * SB).astype(jnp.int32)
        rstart = (bounds[:-1] // 8) * 8      # DMA-aligned tile starts
        end = bounds[1:]
    else:
        order, rstart, end = plan
    # id digits: two exact integer-valued floats — f32 bit patterns of
    # small ints are denormals and would flush; see kernel comment
    hi = (idx // 4096).astype(jnp.float32)
    lo = (idx % 4096).astype(jnp.float32)
    eng = _resolve_pack_engine(P, premerged=order is None)
    # premerged_no_reorder builds the full-width operand with no gather
    # (the wide builder's order=None path)
    builder = _PACK_BUILDERS.get(eng, _pack_wide)
    packed = builder(grads, shows, clks, hi, lo, order, tok, P, PP, W)
    # pad so the last tile's DMA stays in bounds; pad tokens carry row
    # id n_rows, which every block's local-range mask rejects
    pad_block = jnp.zeros((TILE, W), jnp.float32)
    pad_block = pad_block.at[:, PP].set(float(n_rows // 4096))
    pad_block = pad_block.at[:, PP + 1].set(float(n_rows % 4096))
    packed = jnp.concatenate([packed, pad_block], axis=0)
    return packed, rstart, end


def binned_push_geometry(cfg: EmbeddingConfig, n_rows: int):
    """(super_block, n_blocks) for host-side plan building, or None when
    the dispatch keeps another engine (no geometry; wide rows where the
    scatter measures faster — see binned_push_supported; or a forced
    non-binned flags.push_engine) and a plan would be wasted host work
    + H2D.

    flags.push_engine overrides the per-width dispatch for A/B runs:
    "binned_kernel" keeps the kernel at G=1, "xla_scatter" /
    "scatter_accumulate" disable the binned kernel everywhere (the
    fused engine consumes premerged lanes, not block windows).
    """
    geom = _bp_geometry(cfg, n_rows)
    if geom is None:
        return None
    eng = _push_engine_flag()
    if eng in ("xla_scatter", "scatter_accumulate") \
            or (geom[2] == 1 and eng != "binned_kernel"):
        return None
    _, _, _, SB = geom
    return SB, n_rows // SB


# ---------------------------------------------------------------------------
# Push merge-engine registry + resolver.
#
# Three engines cover the push dispatch envelope:
#
#   xla_scatter        scatter-add merge into a full-table accumulator +
#                      one fused XLA update pass over the table. The
#                      no-geometry fallback, and the measured winner for
#                      wide NON-premerged token streams.
#   binned_kernel      the block-binned one-hot MXU merge above + the
#                      fused XLA update pass — the narrow-row (G >= 2)
#                      winner for raw token streams (the headline path).
#   scatter_accumulate the fused row-wise engine below: premerged unique
#                      lanes gather exactly their table rows, the
#                      optimizer applies in VMEM, each row writes back
#                      once — no full-table accumulator, no O(table)
#                      update pass. Serves both the single-shard
#                      premerged path and the routed exchange's
#                      post-all_to_all apply.
#
# The resolver below is THE one selection function (the PR-2 pack_engine
# discipline): the compiled dispatch (sharded.push / exchange.routed_push)
# and the per-point bench record both call it, so the record can never
# name an engine the program does not contain.
# ---------------------------------------------------------------------------

PUSH_ENGINES = ("xla_scatter", "binned_kernel", "scatter_accumulate")

# legacy flag spellings from the pre-fused rounds (the VERDICT r5 A/B
# notes used them); normalized so recorded run commands keep working
_PUSH_ENGINE_ALIASES = {"kernel": "binned_kernel",
                        "scatter": "xla_scatter",
                        "fused": "scatter_accumulate"}


def normalize_push_engine(eng: str) -> str:
    """Canonical engine name for a flags.push_engine value ("auto" and
    already-canonical names pass through; legacy aliases map)."""
    return _PUSH_ENGINE_ALIASES.get(eng, eng)


def _push_engine_flag() -> str:
    from paddlebox_tpu.config import flags as config_flags
    eng = normalize_push_engine(config_flags.push_engine)
    if eng != "auto" and eng not in PUSH_ENGINES:
        raise ValueError(
            f"push_engine={config_flags.push_engine!r} (want 'auto', one "
            f"of {PUSH_ENGINES}, or the legacy 'kernel'/'scatter'/'fused' "
            f"aliases)")
    return eng


def resolve_push_engine(cfg: EmbeddingConfig, n_rows: int, *,
                        premerged: bool, storage_f32: bool = True,
                        table_width: int | None = None) -> str:
    """THE push merge-engine resolver — returns the PUSH_ENGINES member
    the push compiles with for this (cfg, rows, lane contract, storage)
    class. Both the compiled dispatch (sharded.push, exchange.
    routed_push's apply tail) and the per-point bench record call this
    one function, so the record can never name a code path the program
    does not contain (the round-5 unattributable-regression failure
    mode, and the PR-2 pack_engine discipline). Raises on a typo'd
    forced engine: the flag exists for trustworthy A/Bs.

    premerged : the lanes reaching the engine are one-lane-per-unique-row
        (plan_premerge output, a deferred premerged replay, or the routed
        apply's cross-device lane merge). The fused engine REQUIRES this
        contract — without it a forced "scatter_accumulate" falls back to
        the scatter and the record says so.
    storage_f32 : quantized tables keep the binned/scatter engines (the
        fused engine updates f32 rows in place; quant planes dequant →
        update → requant around the storage-agnostic merge acc instead).
    table_width : physical device-table columns (>= cfg.row_width when
        padded); bounds the fused engine's per-row DMA geometry.

    Auto heuristic per (row width class, lane contract, storage, shard):
    premerged f32 lanes on a supported geometry take the fused engine
    (the dim64/dim128/multihot4 floor points — every one of them rides
    premerged lanes); narrow raw token streams keep the binned kernel
    (the measured headline winner); everything else (quant without
    binned geometry, wide raw tokens, off-TPU) scatters. Forced engines
    engage wherever their contract allows — "scatter_accumulate" off-TPU
    runs the identical-math jnp fallback (the A/B and CPU-parity knob),
    and a forced "binned_kernel" bypasses the flags.binned_push enable
    knob — geometry + backend are the contract; an enable flag must not
    silently void an explicit force.
    """
    eng = _push_engine_flag()
    width = int(table_width) if table_width is not None else cfg.row_width
    sa_ok = (premerged and storage_f32
             and scatter_accumulate_geometry(n_rows, width) is not None)
    if eng == "xla_scatter":
        return "xla_scatter"
    if eng == "scatter_accumulate":
        return "scatter_accumulate" if sa_ok else "xla_scatter"
    if eng == "binned_kernel":
        # forced: geometry + backend are the contract — the
        # flags.binned_push enable knob must not be a second SILENT
        # gate on an explicit force (the A/B would measure nothing)
        return ("binned_kernel" if binned_acc_supported(cfg, n_rows)
                else "xla_scatter")
    from paddlebox_tpu.config import flags as config_flags
    binned = (config_flags.binned_push
              and binned_acc_supported(cfg, n_rows))
    # auto: the fused engine first — wherever premerged f32 lanes exist
    # it replaces BOTH the binned kernel's one-hot dots (the multi-hot
    # ~10x overhead) and the scatter's full-table pass (the wide-row
    # floor), on real TPU only (the jnp fallback is a parity tool, not
    # a CPU production win)
    if sa_ok and jax.default_backend() == "tpu":
        return "scatter_accumulate"
    return "binned_kernel" if binned else "xla_scatter"


def lane_groups(cfg: EmbeddingConfig, n_rows: int):
    """G (payload row-groups per 128 dot lanes) for this geometry, or
    None when no kernel geometry exists. G == 1 identifies the wide-row
    widths whose dispatch keeps the XLA scatter (the dedup pre-merge's
    "wide" criterion keys off this)."""
    geom = _bp_geometry(cfg, n_rows)
    return None if geom is None else geom[2]


_geom_fallback_logged: set = set()


def binned_acc_supported(cfg: EmbeddingConfig, n_rows: int) -> bool:
    """Whether binned_merge_acc's geometry engages for this (cfg, rows)
    on the current backend — the storage-agnostic half of
    binned_push_supported (quantized tables check this directly; their
    planes aren't a plain f32 array but the merge acc doesn't care).
    The single engage predicate: binned_push_geometry already folds in
    the G=1 scatter preference."""
    if jax.default_backend() != "tpu":
        return False
    if binned_push_geometry(cfg, n_rows) is None:
        # a geometry miss on an eligible narrow table is a perf loss
        # that must be visible, not silent (ADVICE r2) — same policy as
        # the f32 gate. G=1 misses are deliberate and unwarned.
        geom = _bp_geometry(cfg, n_rows)
        if geom is None:
            key = (n_rows, cfg.grad_width)
            if key not in _geom_fallback_logged:
                _geom_fallback_logged.add(key)
                import warnings
                warnings.warn(
                    f"binned_push geometry unavailable for table rows="
                    f"{n_rows} grad_width={cfg.grad_width}; "
                    f"falling back to the XLA scatter path")
        return False
    return True


def binned_push_supported(table, cfg: EmbeddingConfig) -> bool:
    """Engages on real-TPU f32 tables where the kernel MEASURES faster
    than the XLA scatter: narrow payloads (G >= 2 lane groups, dim <=
    ~56) with a row count fitting the block geometry.

    Wide rows (G = 1) deliberately keep the scatter: the one-hot dot
    work per token grows with SB*PP once lane grouping is gone, and the
    in-step A/B on one v5e (213k tokens, batch 8192) measured scatter
    23.1ms vs kernel 28.1ms at dim 64 and 34.6ms vs 44.0ms at dim 128,
    while the kernel wins 22.9ms vs 39.3ms at dim 32 and 7.7ms vs
    15.5ms at dim 8. Both engines cover the reference's full dispatch
    envelope (box_wrapper.cc:444-461); this picks the faster one per
    width, and bench.py's dim-64/128 matrix points keep the crossover
    measured round over round."""
    if not isinstance(table, jnp.ndarray) or table.dtype != jnp.float32:
        return False
    return binned_acc_supported(cfg, table.shape[0])


def binned_push(table: jnp.ndarray, idx: jnp.ndarray, grads: jnp.ndarray,
                shows: jnp.ndarray, clks: jnp.ndarray,
                cfg: EmbeddingConfig, n_split: int = 3,
                plan=None, interpret: bool = False) -> jnp.ndarray:
    """Merge + in-table optimizer via block-binned one-hot matmuls.

    Semantics match sharded.push's XLA path (duplicates merged before the
    optimizer; out-of-range idx dropped; untouched rows bit-identical) up
    to f32 summation order. n_split: bf16 planes the payload crosses the
    MXU in, built in-kernel from the f32 payload (3 ~= f32-exact; 1 =
    bf16 grads, ~3x fewer dots). Covers the reference's full embedx
    envelope (dims 2..280+, box_wrapper.cc:444-461): narrow rows share
    dot lanes across G row-groups, wide rows take a >128-lane
    accumulator.

    plan: optional (order, rstart, end) token grouping from the host
    (native block_plan, computed in the pack pipeline overlapped with
    device compute — saves the ~2.2ms on-device argsort). Without it the
    grouping runs on device. The kernel only needs tokens GROUPED per
    super-block; order within a block is irrelevant (the matmul merges).
    interpret=True runs the Pallas interpreter (CPU test path).
    """
    n_rows = table.shape[0]
    vma = getattr(jax.typeof(table), "vma", frozenset())
    acc = binned_merge_acc(idx, grads, shows, clks, cfg, n_rows,
                           n_split=n_split, plan=plan,
                           interpret=interpret, vma=vma)
    gw = cfg.grad_width
    new_rows = apply_updates(table, acc[:, :gw], acc[:, gw],
                             acc[:, gw + 1], cfg)
    touched = acc[:, gw + 2] > 0
    return jnp.where(touched[:, None], new_rows, table)


# ---------------------------------------------------------------------------
# Fused gather-pool: the pull-side dual of binned_push.
#
# Multi-hot slots are bottlenecked by the (tokens, pull_width) pulled
# matrix the unfused path materializes between the table gather and the
# per-slot sum pool (the reference fuses exactly this in its
# fused_seqpool_cvm* CUDA kernels): at the bench's mh4d32 point the step
# moves 852k x 35 f32 rows to HBM, pools them, then moves the same-shape
# gradient back — 37.7k examples/s/chip vs the 645k one-hot headline
# (BENCH_r05). This kernel gathers rows from the (HBM-resident) device
# table with per-row async copies and sum-pools them per (example, slot)
# segment while they sit in VMEM, emitting only the pooled
# (B, num_slots, pull_width) output — the per-token matrix never exists
# in HBM. The per-token filters of the reference kernel family
# (need_filter show/clk thresholds — scalar or per-slot —
# embed_threshold, quant_ratio) apply to the gathered rows in VMEM
# before pooling, same math as seqpool_cvm._filter_and_quant.
#
# Layout: tokens of one batch tile land in the gathered scratch at row
# ``l * BB*S + b*S + s`` (pool-position-major), so the pool is L
# contiguous block adds — no strided reads, no scatter. Masked tokens
# are pre-mapped to row NULL_INDEX (all zeros by the working-set
# contract), so padding contributes zeros without a mask operand.
#
# The backward pass does not run in here: the pooled cotangent is
# (B, S, P) — already ~L times smaller than the token matrix — and
# sharded.pooled_grad_tokens expands it per token straight into the
# dedup pre-merge + binned_push pipeline (see PARITY.md "Fused
# gather-pool pull").
#
# On CPU the kernel runs under the Pallas interpreter for the parity
# tests; production CPU paths (and any unsupported geometry) take the
# jnp reference in sharded.fused_pull_pool.
# ---------------------------------------------------------------------------

_GP_VMEM_BUDGET = 4 << 20   # gathered-rows scratch cap (bytes)
_GP_MAX_WIDTH = 512         # table row lanes past this: fall back
_GP_SEMS = 8                # in-flight row DMAs


def gather_pool_geometry(B: int, S: int, L: int, table_width: int,
                         lanes_table: bool = False):
    """Batch-tile size BB for the gather-pool kernel, or None when the
    (batch, slots, slot_len, width) combination doesn't fit its layout
    needs. BB is the largest power of two <= the tile cap dividing B
    whose gathered scratch (L * BB * S rows at the table's padded lane
    width) fits the VMEM budget — bigger tiles amortize the grid
    prologue, smaller ones keep wide rows resident.

    lanes_table: the gather source is a RECEIVED-LANE table (the routed
    path pools per shard from the all_to_all's unique lanes — a
    cap*D x pull_width array, not the n_rows x row_width HBM table the
    64-row cap was tuned on). Lane tables are VMEM-scale and
    pull_width-narrow, so per-row DMA latency amortizes and the grid
    prologue dominates instead: the tile cap doubles to 128 (bounded by
    the idx SMEM block, which grows with BB*S*L) and the same budget
    rule sizes the scratch off the ACTUAL lane width — the retune the
    PR-9 routing deferred (geometry used to inherit the full-table
    tuning wholesale)."""
    if B <= 0 or S <= 0 or L <= 0 or table_width > _GP_MAX_WIDTH:
        return None
    lanes = -(-table_width // 128) * 128
    BB = 128 if lanes_table else 64
    while BB > 1 and (B % BB or L * BB * S * lanes * 4 > _GP_VMEM_BUDGET):
        BB //= 2
    if B % BB or L * BB * S * lanes * 4 > _GP_VMEM_BUDGET:
        return None
    return BB


def gather_pool_supported(cfg: EmbeddingConfig, B: int, S: int, L: int,
                          table_width: int,
                          lanes_table: bool = False) -> bool:
    """Whether the fused gather-pool kernel engages for this geometry on
    the current backend. Real-TPU f32 tables only: quantized storage
    gathers two planes (the jnp reference handles it), and the pull
    gating masks (mf/expand create thresholds) are applied by lookup —
    the kernel skips both, so it must not engage where they matter.
    CPU callers get the jnp reference in sharded.fused_pull_pool; tests
    drive the kernel directly in interpret mode. lanes_table: the
    received-lane geometry (see gather_pool_geometry)."""
    if jax.default_backend() != "tpu":
        return False
    if cfg.storage != "f32":
        return False
    if cfg.mf_create_threshold > 0 or cfg.expand_create_threshold > 0:
        return False
    return gather_pool_geometry(B, S, L, table_width,
                                lanes_table=lanes_table) is not None


def _gather_pool_kernel(idx_ref, thr_ref, table_ref, out_ref, gathered, sem,
                        *, BB: int, S: int, L: int, T: int, P: int,
                        n_rows: int, n_sem: int, need_filter: bool,
                        show_coeff: float, clk_coeff: float,
                        embed_threshold: float, quant_ratio: int,
                        cvm_offset: int):
    """One batch tile: DMA-gather BB*T table rows into the
    pool-position-major scratch, then pool with L contiguous block adds.

    idx_ref : (BB*T,) int32 in SMEM — this tile's (already translated,
              mask-nulled) row ids; the DMA source address for each row.
    thr_ref : (BB*S, 1) f32 — per-(example, slot) need_filter threshold
              (the per-slot diff-thres variant tiled over the tile's
              examples; zeros when need_filter is off).
    The row DMAs run n_sem deep: copy t+n_sem is issued as soon as copy
    t completes (same-slot semaphore reuse forces that order anyway).
    """
    n = BB * T
    BBS = BB * S

    def copy(t):
        row = jnp.minimum(idx_ref[t], n_rows - 1)
        b = t // T
        within = t - b * T
        s = within // L
        l = within - s * L
        dest = l * BBS + b * S + s
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(row, 1), :],
            gathered.at[pl.ds(dest, 1), :],
            sem.at[lax.rem(t, n_sem)])

    for k in range(n_sem):
        copy(k).start()

    def body(t, _):
        copy(t).wait()

        @pl.when(t + n_sem < n)
        def _prefetch():
            copy(t + n_sem).start()

        return 0

    lax.fori_loop(0, n, body, 0)

    acc = None
    for l in range(L):
        x = gathered[l * BBS:(l + 1) * BBS, :]
        keep = None
        if need_filter:
            show, clk = x[:, 0:1], x[:, 1:2]
            keep = ((show - clk) * show_coeff + clk * clk_coeff
                    >= thr_ref[...])
        if embed_threshold > 0.0:
            show, w = x[:, 0:1], x[:, cvm_offset:cvm_offset + 1]
            drop = ((show > embed_threshold)
                    & (jnp.abs(w) < embed_threshold))
            keep = ~drop if keep is None else keep & ~drop
        if quant_ratio > 0:
            # quantize embedx lanes only (lanes past P are sliced away
            # below; quantizing them along for the ride is harmless)
            lane = lax.broadcasted_iota(jnp.int32, x.shape, 1)
            q = jnp.round(x * quant_ratio) / quant_ratio
            x = jnp.where(lane >= cvm_offset + 1, q, x)
        if keep is not None:
            x = x * keep.astype(x.dtype)
        acc = x if acc is None else acc + x
    out_ref[...] = acc[:, :P]


def gather_pool(table: jnp.ndarray, idx: jnp.ndarray, cfg: EmbeddingConfig,
                num_slots: int, slot_len: int, *,
                need_filter: bool = False, show_coeff: float = 0.2,
                clk_coeff: float = 1.0, threshold=0.96,
                embed_threshold: float = 0.0, quant_ratio: int = 0,
                cvm_offset: int = 2, lanes_table: bool = False,
                interpret: bool | None = None) -> jnp.ndarray:
    """Fused gather + per-(example, slot) sum pool over the device table.

    table : (n_rows, W) f32 device table (W >= cfg.pull_width; pad/opt
            columns past pull_width are gathered and discarded). Row
            NULL_INDEX must be the all-zero row — masked/padding tokens
            point there and contribute zeros (callers null idx by mask).
    idx   : (B, S*L) int32 translated indices, slot-major uniform layout
            (token (b, s, l) at column s*L + l — SparseLayout with equal
            max_len per slot).
    threshold may be a scalar or a per-slot (S,) vector (the diff-thres
    variant). Returns (B, S, pull_width) pooled rows; the CVM transform
    applies downstream on this small output (seqpool_cvm.PooledSlots).
    lanes_table selects the received-lane tile geometry (the routed
    path's per-shard pool — see gather_pool_geometry).
    """
    B, T = idx.shape
    S, L = num_slots, slot_len
    assert T == S * L, (T, S, L)
    n_rows, W = table.shape
    BB = gather_pool_geometry(B, S, L, W, lanes_table=lanes_table)
    assert BB is not None, "caller must check gather_pool geometry support"
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    P = cfg.pull_width
    thr = jnp.asarray(threshold, jnp.float32)
    if thr.ndim == 0:
        thr = jnp.broadcast_to(thr, (S,))
    thr_col = jnp.tile(thr, (BB,))[:, None]
    BBS = BB * S
    n_sem = min(_GP_SEMS, BB * T)
    kernel = functools.partial(
        _gather_pool_kernel, BB=BB, S=S, L=L, T=T, P=P, n_rows=n_rows,
        n_sem=n_sem, need_filter=bool(need_filter),
        show_coeff=float(show_coeff), clk_coeff=float(clk_coeff),
        embed_threshold=float(embed_threshold),
        quant_ratio=int(quant_ratio), cvm_offset=int(cvm_offset))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * S, P), jnp.float32),
        grid=(B // BB,),
        in_specs=[
            pl.BlockSpec((BB * T,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((BBS, 1), lambda i: (0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((BBS, P), lambda i: (i, 0)),
        scratch_shapes=[pltpu.VMEM((L * BBS, W), jnp.float32),
                        pltpu.SemaphoreType.DMA((n_sem,))],
        interpret=interpret,
    )(idx.reshape(-1).astype(jnp.int32), thr_col, table)
    return out.reshape(B, S, P)


def binned_merge_acc(idx: jnp.ndarray, grads: jnp.ndarray,
                     shows: jnp.ndarray, clks: jnp.ndarray,
                     cfg: EmbeddingConfig, n_rows: int, n_split: int = 3,
                     plan=None, interpret: bool = False,
                     vma=None) -> jnp.ndarray:
    """The kernel's merge half alone: the (n_rows, grad_width+3) per-row
    accumulator [summed grads, show, clk, touch_count] — identical
    contract to the XLA scatter-add acc, so storage variants (quantized
    tables dequant->update->requant around it) reuse the scatter-free
    merge without the kernel knowing their row encoding."""
    geom = _bp_geometry(cfg, n_rows)
    assert geom is not None, "caller must check binned geometry support"
    P, PP, G, SB = geom
    NB = n_rows // SB
    TILE = _bp_tile(SB, G)
    packed, rstart, end = _bp_pack(idx, grads, shows, clks, geom, TILE,
                                   n_rows, plan)
    W = packed.shape[1]
    if vma is None:
        vma = getattr(jax.typeof(grads), "vma", frozenset())
    RB = SB // G
    AW = _bp_acc_width(G, PP)
    kernel = functools.partial(_binned_acc_kernel, PP=PP,
                               G=G, SB=SB, n_split=n_split, TILE=TILE)
    acc_g = pl.pallas_call(
        kernel,
        out_shape=shape_struct((NB * RB, AW), jnp.float32, vma=vma),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=(NB,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((RB, AW), lambda b, *_: (b, 0)),
            scratch_shapes=[pltpu.VMEM((2, TILE, W), jnp.float32),
                            pltpu.SemaphoreType.DMA((2,))]),
        interpret=interpret,
    )(rstart, end, packed)
    # untangle the grouped layout (fuses into the consumer's update pass)
    return acc_g[:, :G * PP].reshape(NB, RB, G, PP).transpose(
        0, 2, 1, 3).reshape(n_rows, PP)[:, :P]


# ---------------------------------------------------------------------------
# Fused scatter-accumulate: the push-side mirror image of gather_pool.
#
# The scatter and binned engines both end in ONE fused XLA pass over the
# WHOLE table (read + update + where(touched) + write), so their cost has
# an O(table) term that dominates exactly where the recorded floors sit:
# at dim 128 the 528k x ~134 f32 bench table moves ~0.6GB per step through
# that pass while only ~200k unique rows changed, and the binned kernel
# additionally pays one-hot dots that grow ~10x on the multi-hot points
# (BENCH_BEST: dim128 252k, dim64 567k, multihot4_dim32 106k ex/s/chip
# against a 1.2M headline). This kernel takes the premerged unique lanes
# the dedup plan already produces (sharded.plan_premerge — one lane per
# touched row, pads out-of-range) and touches ONLY those rows: per lane,
# DMA the table row into VMEM (n_sem-deep pipelined, the gather_pool
# pattern), apply ``embedding.optim.apply_updates`` row-wise on the tile
# in VMEM — the identical update the XLA pass runs, so numerics match
# bit-for-bit — and DMA the updated row back in place
# (input_output_aliases keeps the table buffer donated). Traffic is
# O(unique rows x row bytes x 2) instead of O(table): the analytic floor
# step_probe.push_floor_analysis holds per bench point.
#
# Lane contract (the premerged form everywhere in this codebase): row ids
# UNIQUE among touched lanes; pad lanes carry out-of-range ids or a zero
# touch flag and are skipped — their write-back DMA never issues, so a
# pad can never clobber a real row's update (the failure mode a clamped
# unconditional write-back exhibits when a real row-0 lane and clamped
# pads interleave). The same kernel serves the single-shard premerged
# push and the routed exchange's post-all_to_all apply: received lanes
# are unique per SOURCE device, so the routed tail merges the <= D lanes
# per row with one compact lane-grade scatter (exchange.routed_push) and
# hands the kernel unique lanes again.
#
# Off-TPU the identical math runs as the jnp reference (gather → row-wise
# apply_updates → one masked scatter write) — the CPU production path and
# the bit-parity baseline; interpret=True drives the Pallas interpreter
# for the hardware-free kernel tests (SURVEY.md §4), except under a
# check_vma shard_map where interpret mode cannot run nontrivial kernels
# (see merge_update) and the jnp reference takes over.
# ---------------------------------------------------------------------------

_SA_TILE = 256          # lanes per grid step ((TILE, W) f32 scratch <= 512KB)
_SA_MAX_WIDTH = 512     # table row lanes past this: fall back
_SA_SEMS = 8            # in-flight row DMAs per direction


def scatter_accumulate_geometry(n_rows: int, table_width: int):
    """Lane-tile size for the fused scatter-accumulate, or None when the
    table doesn't fit the kernel's per-row-DMA layout (rows past the
    width cap stream whole rows the row buffer can't hold)."""
    if n_rows <= 0 or table_width <= 0 or table_width > _SA_MAX_WIDTH:
        return None
    return _SA_TILE


def _scatter_accumulate_kernel(idx_ref, tch_ref, pay_ref, table_ref,
                               out_ref, gathered, sem_in, sem_out, *,
                               TILE: int, n_rows: int, n_sem: int,
                               cfg: EmbeddingConfig):
    """One lane tile: pipelined row gather → row-wise optimizer in VMEM
    → predicated pipelined row write-back.

    idx_ref : (TILE,) int32 SMEM — table row per lane (out-of-range =
              pad; reads clamp to row 0, whose gathered bits are
              discarded because the pad's write never issues).
    tch_ref : (TILE,) int32 SMEM — touch flag per lane; 0 skips the
              write-back entirely (untouched rows keep their exact bits,
              the push contract).
    pay_ref : (TILE, grad_width+2) f32 — [merged grads | show | clk].
    table_ref / out_ref : the (n_rows, W) device table, aliased — rows
              update in place; rows no valid lane names are never
              touched. Lanes are unique among touched lanes, so write
              DMAs never collide and tile order cannot matter.
    """
    def _row(t):
        r = idx_ref[t]
        return jnp.where((r >= 0) & (r < n_rows), r, 0)

    def _valid(t):
        r = idx_ref[t]
        return (r >= 0) & (r < n_rows) & (tch_ref[t] > 0)

    def copy_in(t):
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(_row(t), 1), :],
            gathered.at[pl.ds(t, 1), :], sem_in.at[lax.rem(t, n_sem)])

    for k in range(n_sem):
        copy_in(k).start()

    def gbody(t, _):
        copy_in(t).wait()

        @pl.when(t + n_sem < TILE)
        def _prefetch():
            copy_in(t + n_sem).start()

        return 0

    lax.fori_loop(0, TILE, gbody, 0)
    rows = gathered[...]
    pay = pay_ref[...]
    gw = cfg.grad_width
    # the identical row-wise update the scatter engine's full-table pass
    # runs — elementwise per row, so gather→apply ≡ apply→gather bitwise
    gathered[...] = apply_updates(rows, pay[:, :gw], pay[:, gw],
                                  pay[:, gw + 1], cfg)

    def copy_out(t):
        return pltpu.make_async_copy(
            gathered.at[pl.ds(t, 1), :],
            out_ref.at[pl.ds(_row(t), 1), :], sem_out.at[lax.rem(t, n_sem)])

    # predicated pipeline: lane t's start AND wait share one predicate,
    # and slot t % n_sem is reused only after t's wait ran (or never
    # started) — at most one outstanding copy per slot in every
    # valid/invalid interleaving
    for k in range(n_sem):
        @pl.when(_valid(k))
        def _start(k=k):
            copy_out(k).start()

    def sbody(t, _):
        @pl.when(_valid(t))
        def _wait():
            copy_out(t).wait()

        @pl.when((t + n_sem < TILE) & _valid(t + n_sem))
        def _next():
            copy_out(t + n_sem).start()

        return 0

    lax.fori_loop(0, TILE, sbody, 0)


def scatter_accumulate(table: jnp.ndarray, idx: jnp.ndarray,
                       grads: jnp.ndarray, shows: jnp.ndarray,
                       clks: jnp.ndarray, cfg: EmbeddingConfig,
                       touched: jnp.ndarray | None = None,
                       interpret: bool | None = None) -> jnp.ndarray:
    """Row-wise fused merge-apply over premerged unique lanes.

    table : (n_rows, W) f32 device table (W >= cfg.row_width; pad
            columns pass through apply_updates untouched).
    idx   : (n,) int32 — ONE lane per touched row (plan_premerge's
            contract: ascending unique with out-of-range pads, or any
            unique-among-touched order — the routed apply's lanes).
    grads/shows/clks : merged per-row payload (exact counters included).
    touched : optional per-lane touch flag; default = in-range(idx).
            The routed apply passes the cross-device lane count so its
            dedup-capacity pads (in-range row 0, zero payload) skip the
            write entirely instead of leaning on the null-row fixed
            point.
    interpret : None = jnp reference off-TPU / Mosaic kernel on TPU;
            True = the Pallas interpreter (hardware-free kernel tests).

    Semantics match sharded.push's scatter path bit-for-bit: the same
    apply_updates runs on the same merged values; untouched rows keep
    their exact bits (their row is never DMA'd back). Returns the
    updated table (aliased in place under jit donation).
    """
    n_rows, W = table.shape
    TILE = scatter_accumulate_geometry(n_rows, W)
    assert TILE is not None, \
        "caller must check scatter_accumulate geometry support"
    gw = cfg.grad_width
    idx = idx.astype(jnp.int32)
    if touched is None:
        tch = ((idx >= 0) & (idx < n_rows)).astype(jnp.int32)
    else:
        tch = (touched > 0).astype(jnp.int32)
    pay = jnp.concatenate(
        [grads, shows[:, None], clks[:, None]], axis=1)
    vma = getattr(jax.typeof(table), "vma", frozenset())
    use_kernel = interpret is True or (interpret is None
                                       and jax.default_backend() == "tpu")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not use_kernel or (interpret and vma):
        # the jnp reference: identical math (same gather, same row-wise
        # apply_updates, one masked unique scatter write) — the CPU
        # production path, and the only form interpret mode can run
        # inside a check_vma shard_map (see merge_update)
        safe = jnp.where((idx >= 0) & (idx < n_rows), idx, 0)
        rows = jnp.take(table, safe, axis=0)
        new_rows = apply_updates(rows, pay[:, :gw], pay[:, gw],
                                 pay[:, gw + 1], cfg)
        keep = (tch > 0) & (idx >= 0) & (idx < n_rows)
        # dropped lanes leave the scatter entirely (out-of-range +
        # mode="drop") — a pad must never write a real row's old bits
        # over another lane's update
        wr = jnp.where(keep, idx, n_rows)
        return table.at[wr].set(new_rows, mode="drop")
    n = idx.shape[0]
    pad = (-n) % TILE
    if pad:
        idx = jnp.concatenate(
            [idx, jnp.full((pad,), n_rows, jnp.int32)])
        tch = jnp.concatenate([tch, jnp.zeros((pad,), tch.dtype)])
        pay = jnp.concatenate(
            [pay, jnp.zeros((pad, pay.shape[1]), pay.dtype)])
    n_sem = min(_SA_SEMS, TILE)
    kernel = functools.partial(_scatter_accumulate_kernel, TILE=TILE,
                               n_rows=n_rows, n_sem=n_sem, cfg=cfg)
    return pl.pallas_call(
        kernel,
        out_shape=shape_struct((n_rows, W), table.dtype, vma=vma),
        grid=(idx.shape[0] // TILE,),
        in_specs=[
            pl.BlockSpec((TILE,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((TILE,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((TILE, gw + 2), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.VMEM((TILE, W), jnp.float32),
                        pltpu.SemaphoreType.DMA((n_sem,)),
                        pltpu.SemaphoreType.DMA((n_sem,))],
        input_output_aliases={3: 0},
        interpret=interpret,
    )(idx, tch, pay, table)
