"""Pallas TPU kernels for the embedding-table hot path.

Two generations of kernels live here:

- ``binned_push`` (the production path, flags.binned_push): replaces the
  XLA token scatter-add with block-binned one-hot MXU matmuls that build
  a per-row merge accumulator; the optimizer then applies as ONE fused
  XLA pass over the table — see the section comment. This is the single
  largest perf lever in the framework (train step 15.2ms -> 8.0ms on one
  v5e at batch 8192 across rounds 2-3, 546k -> 1.02M examples/sec/chip;
  the round-3 move of the optimizer OUT of the kernel bought 11.1 ->
  8.0ms alone).
- ``merge_update`` (kept for experiments, default off): fuses only the
  table-update scan after XLA's scatter has built the accumulator.

Gated by ``PBTPU_PALLAS`` (default: on for TPU, off elsewhere).
Measured on one v5e chip, 1M x 13 f32 table, 20% rows touched, adagrad:
XLA path 25.3us, this kernel 19.1us at block_rows=512 (-25%). Narrow rows
pad to 128 lanes in VMEM, so keep block_rows modest: 4096-row blocks of a
13-wide table already blow the 16MB VMEM budget. The kernel reuses
``embedding.optim.apply_updates`` verbatim inside the kernel body, so
numerics are bit-identical to the XLA path and every optimizer
(sgd/adagrad/adam/ftrl) works unchanged.

On CPU the kernel runs in interpret mode — the pure-Python Pallas
interpreter — which is how the tests exercise it without TPU hardware
(SURVEY.md §4: everything must be testable hardware-free).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddlebox_tpu.embedding.config import EmbeddingConfig
from paddlebox_tpu.embedding.optim import apply_updates


def use_pallas() -> bool:
    """Default OFF. The round-1 "+16% end-to-end win" was an artifact of
    timing windows terminated by block_until_ready, which returns early
    over the axon tunnel; with windows terminated by a real device_get,
    the XLA scatter+select path is ~15% FASTER than this kernel (14.9ms vs
    17.5ms DeepFM step, batch 8192, 512k-key working set, one v5e), and
    the kernel's {1,0} operand layout constraint forces padded O(table)
    copies that OOM multi-GB working sets (measured: 3x 5GB copies at
    10.5M x 21 f32). PBTPU_PALLAS=1 re-enables for experiments.

    Read at TRACE time: set it before the first train step compiles.
    Flipping it later does nothing — jitted steps (donated, fed back) never
    retrace, so the already-compiled path keeps running."""
    return os.environ.get("PBTPU_PALLAS") == "1"


def _merge_update_kernel(table_ref, acc_ref, out_ref, *, cfg: EmbeddingConfig):
    rows = table_ref[...]
    acc = acc_ref[...]
    gw = cfg.grad_width
    new_rows = apply_updates(rows, acc[:, :gw], acc[:, gw], acc[:, gw + 1],
                             cfg)
    touched = acc[:, gw + 2] > 0
    out_ref[...] = jnp.where(touched[:, None], new_rows, rows)


@functools.partial(jax.jit, static_argnames=("cfg", "block_rows", "interpret"))
def merge_update(table: jnp.ndarray, acc: jnp.ndarray, cfg: EmbeddingConfig,
                 block_rows: int = 512,
                 interpret: bool | None = None) -> jnp.ndarray:
    """One fused pass of the per-step table update.

    table : (N, row_width) f32
    acc   : (N, grad_width + 3) f32 — summed [grads, show, clk, touch_count]
            per row (the output of the scatter-add merge)
    Returns the updated table; identical to the jnp path in sharded.push.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, w = table.shape
    a = acc.shape[1]
    grid = (pl.cdiv(n, block_rows),)
    # inside shard_map the output varies over the same mesh axes as the
    # table shard (new-style shard_map vma checking)
    vma = getattr(jax.typeof(table), "vma", frozenset())
    if interpret and vma:
        # The Pallas interpreter evaluates the kernel jaxpr with
        # vma-carrying block values, and EVERY op mixing a literal
        # (x * 2.0, x > 0, ...) trips shard_map's vma check — interpret
        # mode fundamentally cannot run nontrivial kernels inside a
        # check_vma shard_map (JAX 0.9.0). Use the identical jnp math on
        # CPU test meshes; Mosaic lowering on real TPU is a custom call
        # and does not hit this.
        gw = cfg.grad_width
        new_rows = apply_updates(table, acc[:, :gw], acc[:, gw],
                                 acc[:, gw + 1], cfg)
        return jnp.where((acc[:, gw + 2] > 0)[:, None], new_rows, table)
    return pl.pallas_call(
        functools.partial(_merge_update_kernel, cfg=cfg),
        out_shape=jax.ShapeDtypeStruct((n, w), table.dtype, vma=vma),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, w), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, a), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, w), lambda i: (i, 0)),
        interpret=interpret,
    )(table, acc)


# ---------------------------------------------------------------------------
# Binned push: the scatter-free merge-update.
#
# XLA's scatter is random-access latency-bound INSIDE the fused step
# (in-step A/B on one v5e, 213k tokens: the scatter step runs 15.5ms vs
# 7.7ms with this kernel at dim 8 — isolated scatter microbenchmarks
# read 100x faster and are a trap; only in-step A/B is decision-grade).
# This kernel replaces it with MXU matmuls: tokens are sorted by row
# id (one argsort), bucketed to contiguous table "super-blocks", and each
# super-block's accumulator is built as one-hot(local_row) @ payload — a
# streaming matmul instead of random-access writes. The optimizer then
# applies OUTSIDE the kernel as one fused full-width XLA pass (the merge +
# update halves of PushMergeCopy, box_wrapper.cu:630-830; see
# _binned_acc_kernel's docstring for why the split wins on TPU).
#
# Exactness: the payload crosses the MXU as an n_split-plane bf16 mantissa
# split computed IN-KERNEL (hi/mid/lo by integer masking, so
# --xla_allow_excess_precision cannot elide the rounding); one-hot entries
# are exact in bf16 and accumulation is f32, so n_split=3 matches the f32
# scatter to ~1e-7 relative (measured 1.6e-7 over a 213k-token batch;
# summation ORDER differs from XLA's scatter, so bitwise equality is not
# expected). n_split=1 rounds grads to bf16 (2x fewer dots).
#
# Packed operand: [payload_f32 (PP lanes) | id_hi | id_lo], PP = payload
# padded to a multiple of 8. Because the mantissa split happens in VMEM,
# the operand width is independent of n_split — ~5x less HBM/DMA traffic
# than the old pre-split 128-lane layout for narrow CTR payloads, and NO
# upper width limit: wide rows (dim 64..280+, the reference's full embedx
# envelope, box_wrapper.cc:444-461) run the same kernel with a >128-lane
# accumulator that Mosaic tiles across lane registers.
#
# Lane packing (narrow rows): G = pow2(128 // PP) row-groups share one
# dot's 128 output lanes (each token's payload is routed into its group's
# lane block), so narrow CTR payloads do not waste ~10x MXU throughput on
# lane padding. Wide rows (PP > 64) take G = 1 and the dot's output lanes
# are the payload itself.
#
# Measured (one v5e, 528k x 13 f32 table, 213k tokens, adagrad, forced-D2H
# repeat-in-one-jit windows): XLA scatter+update ~16.6 ms/call; round-2
# kernel (in-VMEM optimizer) 5.2 ms; round-3 pre-split acc-only 3.6 ms;
# this in-kernel-split layout is measured by bench.py's stage attribution
# (sparse_push) and the dim-64/128 matrix points.
# ---------------------------------------------------------------------------

_BP_TILE = 1024          # tokens per DMA/matmul tile
_BP_MAX_PP = 512         # accumulator lane cap (dim 280 -> PP 288)


def _bp_lanes(cfg: EmbeddingConfig, rows: int):
    """Shared lane geometry: (P, PP, G, target_SB) or None past the
    width cap. The single source of truth for both the kernel geometry
    and the working-set row alignment — they MUST agree or shard row
    counts desynchronize from the kernel's actual block choice.

    G = largest power of two <= 128 // PP: lane routing only needs
    G * PP <= 128, and a non-pow2 G (PP=24 -> 128//24=5) would fail the
    SB % G divisibility and silently lose the kernel for those widths.
    PP > 64 -> G=1: the dot's output lanes are the payload itself
    (Mosaic tiles >128-lane accumulators across lane registers).

    target_SB trades one-hot dot FLOPs against grid overhead: each
    token's one-hot row is RB = SB/G wide (work ~ tokens * RB * PP per
    plane) while each block costs a fixed ~20us of DMA/prologue (cost ~
    n_rows/SB) — so SB* ~ sqrt(c * n_rows * 128/PP), c fitted on v5e
    (~3; for PP <= 64 the 128/PP ratio equals G up to pow2 rounding, so
    this reduces to the round-3 sqrt(3*G*n_rows)). A 10.5M-row table at
    SB=4096 is 2560 mostly-empty grid steps (measured +2.6ms); the
    bench's 557k-row table at SB=16384 wastes 4x MXU work (measured
    +1.4ms)."""
    P = cfg.grad_width + 3
    PP = -(-P // 8) * 8
    if PP > _BP_MAX_PP:
        return None
    G = max(1, 1 << ((128 // PP).bit_length() - 1)) if PP <= 128 else 1
    target = int((3.0 * max(1, rows) * 128.0 / PP) ** 0.5)
    return P, PP, G, target


def _bp_geometry(cfg: EmbeddingConfig, n_rows: int):
    """(payload P, padded PP, groups G, super-block SB) or None if the
    table doesn't fit the kernel's divisibility/width needs."""
    lanes = _bp_lanes(cfg, n_rows)
    if lanes is None:
        return None
    P, PP, G, target = lanes
    # nearest dividing block to target_SB. RB = SB/G is capped at 2048:
    # the (TILE, RB) one-hot operand blew v5e's 16MB scoped-vmem limit
    # at RB=4096 (the tile also halves past RB 1024 — _bp_tile).
    best = None
    SB = min(2048 * G, 1 << 16)
    while SB >= 512:
        if n_rows % SB == 0 and SB % G == 0:
            if best is None or abs(SB - target) < abs(best - target):
                best = SB
        SB //= 2
    if best is None:
        return None
    return P, PP, G, best


def bp_row_alignment(cfg: EmbeddingConfig, rows: int) -> int:
    """Row-count alignment that lets `_bp_geometry` pick its TARGET
    super-block for a table of ~`rows` rows: the power of two nearest
    target_SB, clamped to [4096, RB-cap]. Working-set builders align
    shard row counts to this — big tables get big-block divisibility,
    small tables keep the cheap 4096 alignment."""
    lanes = _bp_lanes(cfg, rows)
    if lanes is None:
        return 4096
    _, _, G, target = lanes
    pow2 = 1 << max(0, target.bit_length() - 1)
    if target - pow2 > 2 * pow2 - target:       # round to nearest pow2
        pow2 <<= 1
    return max(4096, min(pow2, 2048 * G, 1 << 16))


def _bp_tile(SB: int, G: int) -> int:
    """Tokens per DMA/matmul tile: halved for big blocks so the
    (TILE, RB) one-hot operand stays ~2MB."""
    return _BP_TILE if SB // G <= 1024 else _BP_TILE // 2


def _bp_acc_width(G: int, PP: int) -> int:
    """Accumulator lane count: G*PP for narrow rows; padded to a full
    128-lane tile past one tile (Mosaic rejects multi-tile shapes with
    odd tails, and a 136-lane dot already costs two 128-lane MXU blocks,
    so the padding is free)."""
    gp = G * PP
    return gp if gp <= 128 else -(-gp // 128) * 128


def _binned_acc_kernel(rstart_ref, end_ref, packed_ref, acc_ref,
                       pack_s, sem, *, PP: int, G: int, SB: int,
                       n_split: int, TILE: int):
    """Per-block merge accumulator via one-hot MXU matmuls.

    Writes this block's accumulator in GROUPED layout (RB, G*PP) — row
    ``local % RB``, lane block ``(local // RB) * PP`` — which the caller
    untangles with a reshape/transpose that XLA fuses into the table
    update. The optimizer deliberately does NOT run in here: a
    (block, group)-tiled elementwise chain wastes ~90% of each VPU lane
    on narrow CTR rows, while the same update as ONE fused XLA pass over
    the whole table runs at full width (measured on one v5e, 528k x 13
    adagrad: in-kernel update ~3.5ms of the old 5.2ms kernel vs 0.5ms as
    a fused XLA pass over the grouped acc).

    The bf16 mantissa planes are built HERE from the f32 payload (cheap
    VPU integer masking on the tile) rather than pre-split host/XLA-side:
    the packed operand carries each payload value once, so DMA traffic is
    ~(PP+2)/128 of the old pre-split layout and the payload-prep XLA
    chain disappears from the step."""
    RB = SB // G
    b = pl.program_id(0)
    start = rstart_ref[b]
    endv = end_ref[b]
    acc_ref[...] = jnp.zeros_like(acc_ref)
    n_t = lax.div(endv - start + TILE - 1, TILE)

    def _copy(t):
        slot = lax.rem(t, 2)
        # rstart entries are //8*8-aligned by construction (plan builder
        # and device fallback both); Mosaic needs the hint to prove the
        # row slice respects (8,128) memref tiling for W > 128 operands
        row0 = pl.multiple_of(start + t * TILE, 8)
        return pltpu.make_async_copy(
            packed_ref.at[pl.ds(row0, TILE), :],
            pack_s.at[slot], sem.at[slot])

    # double-buffered DMA: tile t+1 streams in while tile t computes
    @pl.when(n_t > 0)
    def _prefetch_first():
        _copy(0).start()

    def body(t, _):
        @pl.when((t + 1) < n_t)
        def _prefetch_next():
            _copy(t + 1).start()

        _copy(t).wait()
        packed = pack_s[lax.rem(t, 2)]
        off = start + t * TILE
        # row id rides the two lanes PAST the payload as two exact
        # integer-valued floats (hi*4096+lo): f32 BIT patterns of small
        # ints are denormals and XLA flushes them, so a bitcast column
        # would read back as zeros
        tok = (packed[:, PP:PP + 1].astype(jnp.int32) * 4096
               + packed[:, PP + 1:PP + 2].astype(jnp.int32))
        pos = lax.broadcasted_iota(jnp.int32, (TILE, 1), 0) + off
        local = tok - b * SB
        valid = (pos < endv) & (local >= 0) & (local < SB)
        grp = jnp.where(valid, local // RB, G)
        within = jnp.where(valid, local % RB, RB)
        oh = (within == lax.broadcasted_iota(
            jnp.int32, (TILE, RB), 1)).astype(jnp.bfloat16)
        AW = _bp_acc_width(G, PP)
        lane_grp = lax.broadcasted_iota(jnp.int32, (TILE, AW), 1) // PP
        # in-kernel mantissa split: plane s holds the top 16 bits of the
        # running residual (exact in bf16); the LAST plane is the raw
        # residual, which after two maskings has <= 8 significant bits
        # (exact) and for n_split=1 is the full payload (bf16-rounded).
        # Wide rows (G=1, AW > PP) split the packed tile whole — the id /
        # padding lanes past PP are split along for the ride; their acc
        # lanes are never read by the caller's [:, :P] slice.
        rem = packed[:, 0:PP] if G > 1 else packed[:, 0:AW]
        for s in range(n_split):
            if s == n_split - 1:
                plane = rem
            else:
                plane = lax.bitcast_convert_type(
                    lax.bitcast_convert_type(rem, jnp.int32)
                    & jnp.int32(-65536), jnp.float32)
                rem = rem - plane
            wide = jnp.tile(plane, (1, G)) if G > 1 else plane
            routed = jnp.where(lane_grp == grp, wide, 0.0)
            acc_ref[...] += lax.dot_general(
                oh, routed.astype(jnp.bfloat16), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return 0

    lax.fori_loop(0, n_t, body, 0)


def _bp_pack(idx, grads, shows, clks, geom, TILE: int, n_rows: int,
             plan=None):
    """Build the kernel's packed operand: tokens grouped by super-block,
    each row ``[payload_f32 (PP lanes) | id_hi | id_lo]`` padded to a
    multiple of 8 lanes (then to whole 128-lane tiles for the DMA).
    Split out so bench.py's stage attribution can time the prep
    separately from the kernel.

    The token gather (``[order]``) runs at the FULL padded width: v5e
    row gathers from 14..63-lane sources are 3-8x slower per row than
    from >=64-lane ones (852k-token sweep: 23.2ms at 40 lanes vs 3.6ms
    at 128), so the payload is padded/id-tagged BEFORE the reorder —
    one extra elementwise pass, ~6x off the multi-hot pack cost."""
    P, PP, G, SB = geom
    NB = n_rows // SB
    tok = idx.shape[0]
    # Mosaic DMA slices must be 128-lane aligned (memref tiling (1,128));
    # narrow payloads pad up to one lane tile, wide ones to the next
    W = -(-(PP + 2) // 128) * 128
    order = rstart = end = None
    if plan is None:
        order = jnp.argsort(idx)
        s_idx = idx[order]
        bounds = jnp.searchsorted(
            s_idx,
            jnp.arange(NB + 1, dtype=jnp.int32) * SB).astype(jnp.int32)
        rstart = (bounds[:-1] // 8) * 8      # DMA-aligned tile starts
        end = bounds[1:]
    else:
        order, rstart, end = plan
    # id digits: two exact integer-valued floats — f32 bit patterns of
    # small ints are denormals and would flush; see kernel comment
    hi = (idx // 4096).astype(jnp.float32)
    lo = (idx % 4096).astype(jnp.float32)
    if P < 16 and order is not None:
        # narrow payloads gather fast at their logical width (v5e:
        # 12-13-lane row gathers ~5-10ns/row) — reorder first, pad after
        payload = jnp.concatenate(
            [grads, shows[:, None], clks[:, None],
             jnp.ones((tok, 1), jnp.float32)], axis=1)
        s_pay = jnp.take(payload, order, axis=0)
        packed = jnp.concatenate(
            [s_pay, jnp.zeros((tok, PP - P), jnp.float32),
             jnp.take(hi, order)[:, None], jnp.take(lo, order)[:, None],
             jnp.zeros((tok, W - PP - 2), jnp.float32)], axis=1)
    else:
        # 16..63-lane gathers are pathological (3-8x/row) — pack to the
        # full 128-lane-tile width FIRST, then one fast wide gather
        pay_full = jnp.concatenate(
            [grads, shows[:, None], clks[:, None],
             jnp.ones((tok, 1), jnp.float32),
             jnp.zeros((tok, PP - P), jnp.float32),
             hi[:, None], lo[:, None],
             jnp.zeros((tok, W - PP - 2), jnp.float32)], axis=1)
        packed = (pay_full if order is None        # pre-merged: sorted
                  else jnp.take(pay_full, order, axis=0))
    # pad so the last tile's DMA stays in bounds; pad tokens carry row
    # id n_rows, which every block's local-range mask rejects
    pad_block = jnp.zeros((TILE, W), jnp.float32)
    pad_block = pad_block.at[:, PP].set(float(n_rows // 4096))
    pad_block = pad_block.at[:, PP + 1].set(float(n_rows % 4096))
    packed = jnp.concatenate([packed, pad_block], axis=0)
    return packed, rstart, end


def binned_push_geometry(cfg: EmbeddingConfig, n_rows: int):
    """(super_block, n_blocks) for host-side plan building, or None when
    the dispatch keeps the scatter (no geometry, or wide rows where the
    scatter measures faster — see binned_push_supported) and a plan
    would be wasted host work + H2D.

    flags.push_engine overrides the per-width dispatch for A/B runs:
    "kernel" keeps the kernel at G=1, "scatter" disables it everywhere.
    """
    geom = _bp_geometry(cfg, n_rows)
    if geom is None:
        return None
    from paddlebox_tpu.config import flags as config_flags
    eng = config_flags.push_engine
    if eng == "scatter" or (geom[2] == 1 and eng != "kernel"):
        return None
    _, _, _, SB = geom
    return SB, n_rows // SB


def lane_groups(cfg: EmbeddingConfig, n_rows: int):
    """G (payload row-groups per 128 dot lanes) for this geometry, or
    None when no kernel geometry exists. G == 1 identifies the wide-row
    widths whose dispatch keeps the XLA scatter (the dedup pre-merge's
    "wide" criterion keys off this)."""
    geom = _bp_geometry(cfg, n_rows)
    return None if geom is None else geom[2]


_geom_fallback_logged: set = set()


def binned_acc_supported(cfg: EmbeddingConfig, n_rows: int) -> bool:
    """Whether binned_merge_acc's geometry engages for this (cfg, rows)
    on the current backend — the storage-agnostic half of
    binned_push_supported (quantized tables check this directly; their
    planes aren't a plain f32 array but the merge acc doesn't care).
    The single engage predicate: binned_push_geometry already folds in
    the G=1 scatter preference."""
    if jax.default_backend() != "tpu":
        return False
    if binned_push_geometry(cfg, n_rows) is None:
        # a geometry miss on an eligible narrow table is a perf loss
        # that must be visible, not silent (ADVICE r2) — same policy as
        # the f32 gate. G=1 misses are deliberate and unwarned.
        geom = _bp_geometry(cfg, n_rows)
        if geom is None:
            key = (n_rows, cfg.grad_width)
            if key not in _geom_fallback_logged:
                _geom_fallback_logged.add(key)
                import warnings
                warnings.warn(
                    f"binned_push geometry unavailable for table rows="
                    f"{n_rows} grad_width={cfg.grad_width}; "
                    f"falling back to the XLA scatter path")
        return False
    return True


def binned_push_supported(table, cfg: EmbeddingConfig) -> bool:
    """Engages on real-TPU f32 tables where the kernel MEASURES faster
    than the XLA scatter: narrow payloads (G >= 2 lane groups, dim <=
    ~56) with a row count fitting the block geometry.

    Wide rows (G = 1) deliberately keep the scatter: the one-hot dot
    work per token grows with SB*PP once lane grouping is gone, and the
    in-step A/B on one v5e (213k tokens, batch 8192) measured scatter
    23.1ms vs kernel 28.1ms at dim 64 and 34.6ms vs 44.0ms at dim 128,
    while the kernel wins 22.9ms vs 39.3ms at dim 32 and 7.7ms vs
    15.5ms at dim 8. Both engines cover the reference's full dispatch
    envelope (box_wrapper.cc:444-461); this picks the faster one per
    width, and bench.py's dim-64/128 matrix points keep the crossover
    measured round over round."""
    if not isinstance(table, jnp.ndarray) or table.dtype != jnp.float32:
        return False
    return binned_acc_supported(cfg, table.shape[0])


def binned_push(table: jnp.ndarray, idx: jnp.ndarray, grads: jnp.ndarray,
                shows: jnp.ndarray, clks: jnp.ndarray,
                cfg: EmbeddingConfig, n_split: int = 3,
                plan=None, interpret: bool = False) -> jnp.ndarray:
    """Merge + in-table optimizer via block-binned one-hot matmuls.

    Semantics match sharded.push's XLA path (duplicates merged before the
    optimizer; out-of-range idx dropped; untouched rows bit-identical) up
    to f32 summation order. n_split: bf16 planes the payload crosses the
    MXU in, built in-kernel from the f32 payload (3 ~= f32-exact; 1 =
    bf16 grads, ~3x fewer dots). Covers the reference's full embedx
    envelope (dims 2..280+, box_wrapper.cc:444-461): narrow rows share
    dot lanes across G row-groups, wide rows take a >128-lane
    accumulator.

    plan: optional (order, rstart, end) token grouping from the host
    (native block_plan, computed in the pack pipeline overlapped with
    device compute — saves the ~2.2ms on-device argsort). Without it the
    grouping runs on device. The kernel only needs tokens GROUPED per
    super-block; order within a block is irrelevant (the matmul merges).
    interpret=True runs the Pallas interpreter (CPU test path).
    """
    n_rows = table.shape[0]
    vma = getattr(jax.typeof(table), "vma", frozenset())
    acc = binned_merge_acc(idx, grads, shows, clks, cfg, n_rows,
                           n_split=n_split, plan=plan,
                           interpret=interpret, vma=vma)
    gw = cfg.grad_width
    new_rows = apply_updates(table, acc[:, :gw], acc[:, gw],
                             acc[:, gw + 1], cfg)
    touched = acc[:, gw + 2] > 0
    return jnp.where(touched[:, None], new_rows, table)


def binned_merge_acc(idx: jnp.ndarray, grads: jnp.ndarray,
                     shows: jnp.ndarray, clks: jnp.ndarray,
                     cfg: EmbeddingConfig, n_rows: int, n_split: int = 3,
                     plan=None, interpret: bool = False,
                     vma=None) -> jnp.ndarray:
    """The kernel's merge half alone: the (n_rows, grad_width+3) per-row
    accumulator [summed grads, show, clk, touch_count] — identical
    contract to the XLA scatter-add acc, so storage variants (quantized
    tables dequant->update->requant around it) reuse the scatter-free
    merge without the kernel knowing their row encoding."""
    geom = _bp_geometry(cfg, n_rows)
    assert geom is not None, "caller must check binned geometry support"
    P, PP, G, SB = geom
    NB = n_rows // SB
    TILE = _bp_tile(SB, G)
    packed, rstart, end = _bp_pack(idx, grads, shows, clks, geom, TILE,
                                   n_rows, plan)
    W = packed.shape[1]
    if vma is None:
        vma = getattr(jax.typeof(grads), "vma", frozenset())
    RB = SB // G
    AW = _bp_acc_width(G, PP)
    kernel = functools.partial(_binned_acc_kernel, PP=PP,
                               G=G, SB=SB, n_split=n_split, TILE=TILE)
    acc_g = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((NB * RB, AW), jnp.float32,
                                       vma=vma),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=(NB,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((RB, AW), lambda b, *_: (b, 0)),
            scratch_shapes=[pltpu.VMEM((2, TILE, W), jnp.float32),
                            pltpu.SemaphoreType.DMA((2,))]),
        interpret=interpret,
    )(rstart, end, packed)
    # untangle the grouped layout (fuses into the consumer's update pass)
    return acc_g[:, :G * PP].reshape(NB, RB, G, PP).transpose(
        0, 2, 1, 3).reshape(n_rows, PP)[:, :P]
