"""Pallas TPU kernels for the embedding-table hot path.

The device-side cost of ``sharded.push`` has two parts: the token
scatter-add (XLA's scatter is fine for it) and the O(N·row_width) table
merge-update scan — read every row, apply the in-table optimizer where
touched, write every row. XLA materializes the intermediate ``new_rows`` and
``where`` buffers between fusions; the Pallas kernel below does the whole
merge-update as ONE double-buffered read-modify-write pass over row blocks
(pallas_call's grid pipeline overlaps the HBM DMAs with the VPU math), so
per step the table moves through HBM exactly twice (read + write).

Gated by ``PBTPU_PALLAS`` (default: on for TPU, off elsewhere).
Measured on one v5e chip, 1M x 13 f32 table, 20% rows touched, adagrad:
XLA path 25.3us, this kernel 19.1us at block_rows=512 (-25%). Narrow rows
pad to 128 lanes in VMEM, so keep block_rows modest: 4096-row blocks of a
13-wide table already blow the 16MB VMEM budget. The kernel reuses
``embedding.optim.apply_updates`` verbatim inside the kernel body, so
numerics are bit-identical to the XLA path and every optimizer
(sgd/adagrad/adam/ftrl) works unchanged.

On CPU the kernel runs in interpret mode — the pure-Python Pallas
interpreter — which is how the tests exercise it without TPU hardware
(SURVEY.md §4: everything must be testable hardware-free).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddlebox_tpu.embedding.config import EmbeddingConfig
from paddlebox_tpu.embedding.optim import apply_updates


def use_pallas() -> bool:
    """Default OFF. The round-1 "+16% end-to-end win" was an artifact of
    timing windows terminated by block_until_ready, which returns early
    over the axon tunnel; with windows terminated by a real device_get,
    the XLA scatter+select path is ~15% FASTER than this kernel (14.9ms vs
    17.5ms DeepFM step, batch 8192, 512k-key working set, one v5e), and
    the kernel's {1,0} operand layout constraint forces padded O(table)
    copies that OOM multi-GB working sets (measured: 3x 5GB copies at
    10.5M x 21 f32). PBTPU_PALLAS=1 re-enables for experiments.

    Read at TRACE time: set it before the first train step compiles.
    Flipping it later does nothing — jitted steps (donated, fed back) never
    retrace, so the already-compiled path keeps running."""
    return os.environ.get("PBTPU_PALLAS") == "1"


def _merge_update_kernel(table_ref, acc_ref, out_ref, *, cfg: EmbeddingConfig):
    rows = table_ref[...]
    acc = acc_ref[...]
    gw = cfg.grad_width
    new_rows = apply_updates(rows, acc[:, :gw], acc[:, gw], acc[:, gw + 1],
                             cfg)
    touched = acc[:, gw + 2] > 0
    out_ref[...] = jnp.where(touched[:, None], new_rows, rows)


@functools.partial(jax.jit, static_argnames=("cfg", "block_rows", "interpret"))
def merge_update(table: jnp.ndarray, acc: jnp.ndarray, cfg: EmbeddingConfig,
                 block_rows: int = 512,
                 interpret: bool | None = None) -> jnp.ndarray:
    """One fused pass of the per-step table update.

    table : (N, row_width) f32
    acc   : (N, grad_width + 3) f32 — summed [grads, show, clk, touch_count]
            per row (the output of the scatter-add merge)
    Returns the updated table; identical to the jnp path in sharded.push.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, w = table.shape
    a = acc.shape[1]
    grid = (pl.cdiv(n, block_rows),)
    # inside shard_map the output varies over the same mesh axes as the
    # table shard (new-style shard_map vma checking)
    vma = getattr(jax.typeof(table), "vma", frozenset())
    if interpret and vma:
        # The Pallas interpreter evaluates the kernel jaxpr with
        # vma-carrying block values, and EVERY op mixing a literal
        # (x * 2.0, x > 0, ...) trips shard_map's vma check — interpret
        # mode fundamentally cannot run nontrivial kernels inside a
        # check_vma shard_map (JAX 0.9.0). Use the identical jnp math on
        # CPU test meshes; Mosaic lowering on real TPU is a custom call
        # and does not hit this.
        gw = cfg.grad_width
        new_rows = apply_updates(table, acc[:, :gw], acc[:, gw],
                                 acc[:, gw + 1], cfg)
        return jnp.where((acc[:, gw + 2] > 0)[:, None], new_rows, table)
    return pl.pallas_call(
        functools.partial(_merge_update_kernel, cfg=cfg),
        out_shape=jax.ShapeDtypeStruct((n, w), table.dtype, vma=vma),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, w), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, a), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, w), lambda i: (i, 0)),
        interpret=interpret,
    )(table, acc)
