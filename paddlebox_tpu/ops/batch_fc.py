"""Batch FC — per-group fully-connected layers in one op.

Reference: ``batch_fc`` op (operators/batch_fc_op.cu): input
(slot_pairs_num, ins_num, in_dim) runs `slot_pairs_num` independent FCs with
weights (slot_pairs_num, in_dim, out_dim) and bias (slot_pairs_num, out_dim),
optionally ReLU. Used for per-rank towers. One einsum on TPU — the MXU
batches it natively.
"""

from __future__ import annotations

import jax.numpy as jnp


def batch_fc(x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray | None = None,
             activation: str | None = None) -> jnp.ndarray:
    """x (G, N, I) @ w (G, I, O) [+ b (G, O)] → (G, N, O)."""
    out = jnp.einsum("gni,gio->gno", x, w)
    if b is not None:
        out = out + b[:, None, :]
    if activation == "relu":
        out = jnp.maximum(out, 0.0)
    elif activation is not None:
        raise ValueError(f"unsupported activation {activation!r}")
    return out
