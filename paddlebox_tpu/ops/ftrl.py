"""Shared FTRL-proximal update step.

One elementwise kernel used by both the dense optimizer registry
(train/optimizers.py) and the in-table sparse optimizer
(embedding/optim.py), so the two paths cannot drift. Same rule as the
reference's ``ftrl_op`` (operators/optimizers/ftrl_op.h, lr_power=-0.5):

    new_n = n + g^2
    sigma = (sqrt(new_n) - sqrt(n)) / alpha
    new_z = z + g - sigma * w
    new_w = -shrink(new_z, l1) / ((beta + sqrt(new_n)) / alpha + l2)
"""

from __future__ import annotations

import jax.numpy as jnp


def ftrl_step(g, z, n, w, lr: float, l1: float, l2: float, beta: float):
    """Return (new_w, new_z, new_n); all args broadcast elementwise."""
    new_n = n + g * g
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * w
    shrink = jnp.maximum(jnp.abs(new_z) - l1, 0.0)
    new_w = -jnp.sign(new_z) * shrink / ((beta + jnp.sqrt(new_n)) / lr + l2)
    return new_w, new_z, new_n
