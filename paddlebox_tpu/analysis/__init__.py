"""pblint — AST-based project-invariant linter.

The reliability arcs of this codebase (PRs 3-7) each ended with a
"review-pass hardening" list: a human reviewer catching violations of
invariants the codebase already believed in — raw writes where the
atomic tmp->fsync->replace discipline was required, donefile lines
written outside the one sanctioned appender, bare ``threading.Thread``
spawns that strip telemetry context, faultpoints outside the closed
kill-matrix registry, flags drifting from the registry. This package
encodes those invariants as machine-checked rules, the same move
BENCH_BEST.json made for performance: a recorded gate instead of
reviewer memory.

Pieces:

- :mod:`paddlebox_tpu.analysis.core` — the rule framework: per-file AST
  contexts, a cross-file :class:`~paddlebox_tpu.analysis.core.ProjectIndex`
  (flags, faultpoints, test references), the waiver mechanism
  (``# pblint: disable=<rule>[,<rule>] -- <reason>``, reason mandatory),
  and the findings/baseline model.
- :mod:`paddlebox_tpu.analysis.rules` — the rules themselves, each
  grounded in a real prior incident (see docs/INVARIANTS.md).
- :mod:`paddlebox_tpu.analysis.lint` — the CLI::

      python -m paddlebox_tpu.analysis.lint [paths...]

  Exit 0 = clean, 1 = unwaived findings, 2 = usage error; one
  ``file:line rule message`` line per finding.

Deliberately import-light: nothing here touches jax (or any other
package module), so the lint gate runs on a bare CPU box in well under
the tier-1 budget — tests/test_lint_clean.py proves the CLI passes with
jax imports blocked outright.
"""

from paddlebox_tpu.analysis.core import (  # noqa: F401
    Finding,
    Linter,
    Project,
    load_baseline,
)
from paddlebox_tpu.analysis.rules import ALL_RULES  # noqa: F401
