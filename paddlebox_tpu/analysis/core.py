"""pblint rule framework: file contexts, cross-file index, waivers, baseline.

Stdlib-only by design (``ast`` + ``tokenize``): the lint gate must run on a
bare CPU box without importing jax or any package module it checks — a
linter that needs the full training stack up cannot gate a broken tree.

Vocabulary:

- :class:`FileContext` — one parsed source file: AST, repo-relative path,
  and the waivers extracted from its comments.
- :class:`Project` — where the project's load-bearing files live (flags
  registry, faultpoint registry, donefile writer, durability modules).
  Defaults describe this repository; tests construct fixture projects.
- :class:`ProjectIndex` — the cross-file facts rules consult: flag fields
  and every read of them, faultpoint registries and every hit site, the
  string literals and registry references appearing under ``tests/``.
- :class:`Rule` — per-file visitor (:meth:`Rule.visit_file`) plus an
  optional whole-project check (:meth:`Rule.check_project`) for facts no
  single file can establish (dead flags, untested kill points).

Waivers: ``# pblint: disable=<rule>[,<rule>] -- <reason>`` — trailing on
the offending line, or standalone on the line(s) immediately above it.
The reason is mandatory; a waiver without one raises a ``bad-waiver``
finding AND does not suppress anything, so a waiver can never be cheaper
than a fix without leaving a recorded why.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Iterable, Iterator

# rules synthesized by the framework itself (waiver problems, unparseable
# files) — always active, not subject to --rules selection
BAD_WAIVER = "bad-waiver"
PARSE_ERROR = "parse-error"

_WAIVER_RE = re.compile(
    r"#\s*pblint:\s*disable=([A-Za-z0-9_,\-]+)"  # rule list
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$")        # mandatory reason


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at one source location."""

    file: str          # repo-relative, forward slashes
    line: int
    rule: str
    message: str

    def key(self) -> tuple[str, str, str]:
        """Baseline identity: line numbers drift, (file, rule, message)
        survives unrelated edits above the finding."""
        return (self.file, self.rule, self.message)

    def render(self) -> str:
        return f"{self.file}:{self.line} {self.rule} {self.message}"


@dataclasses.dataclass
class Project:
    """Where the linted project keeps its load-bearing files.

    All paths are repo-relative with forward slashes; entries ending in
    ``/`` match as directory prefixes. Defaults describe this repository;
    tests build fixture projects in tmp dirs with the same shape.
    """

    root: str
    package: str = "paddlebox_tpu"
    durability_modules: tuple[str, ...] = (
        "paddlebox_tpu/utils/checkpoint.py",
        "paddlebox_tpu/utils/pass_ckpt.py",
        "paddlebox_tpu/serving/artifact.py",
        "paddlebox_tpu/embedding/store.py",
        "paddlebox_tpu/embedding/spill_store.py",
        "paddlebox_tpu/data/archive.py",
        "paddlebox_tpu/fleet/",
    )
    thread_context_module: str = "paddlebox_tpu/monitor/context.py"
    donefile_writers: tuple[str, ...] = ("paddlebox_tpu/fleet/fleet_util.py",)
    donefile_appender: str = "append_donefile"
    flags_module: str = "paddlebox_tpu/config.py"
    flags_class: str = "Flags"
    faultpoint_module: str = "paddlebox_tpu/utils/faultpoint.py"
    faultpoint_registries: tuple[str, ...] = (
        "POINTS", "ELASTIC_POINTS", "SERVING_POINTS", "EXCHANGE_POINTS",
        "MONITOR_POINTS")
    # closed hub event/span NAME registry (monitor/names.py) — the
    # event-registry rule checks every literal monitor.event/span site
    # against the union of these tuples
    event_registry_module: str = "paddlebox_tpu/monitor/names.py"
    event_registries: tuple[str, ...] = ("EVENT_NAMES", "SPAN_NAMES")
    tests_dir: str = "tests"
    # extra trees indexed for *references* (flag reads, faultpoint names)
    # but never linted themselves
    aux_reference_paths: tuple[str, ...] = (
        "bench.py", "bench_spill.py", "examples")

    @classmethod
    def discover(cls, start: str, package: str = "paddlebox_tpu"
                 ) -> "Project":
        """Walk up from ``start`` to the directory holding the package's
        flags module — that directory is the repo root."""
        d = os.path.abspath(start)
        if os.path.isfile(d):
            d = os.path.dirname(d)
        while True:
            if os.path.isfile(os.path.join(d, package, "config.py")):
                return cls(root=d, package=package)
            parent = os.path.dirname(d)
            if parent == d:
                # no marker found: fall back to the start directory so
                # relpaths are at least stable
                return cls(root=os.path.abspath(start) if os.path.isdir(
                    start) else os.path.dirname(os.path.abspath(start)),
                    package=package)
            d = parent

    def relpath(self, abspath: str) -> str:
        return os.path.relpath(os.path.abspath(abspath),
                               self.root).replace(os.sep, "/")

    def in_durability_module(self, relpath: str) -> bool:
        for m in self.durability_modules:
            if (relpath == m) or (m.endswith("/") and relpath.startswith(m)):
                return True
        return False


class FileContext:
    """One parsed source file + its waivers."""

    def __init__(self, abspath: str, relpath: str, source: str,
                 tree: ast.AST, waivers: dict[int, dict[str, str]],
                 waiver_problems: list[Finding]):
        self.abspath = abspath
        self.relpath = relpath
        self.source = source
        self.tree = tree
        # line -> {rule: reason}
        self.waivers = waivers
        self.waiver_problems = waiver_problems
        self._imports: "list[tuple[str, str, str | None, str]] | None" \
            = None

    @property
    def import_table(self) -> "list[tuple[str, str, str | None, str]]":
        """(kind, module, name, local_alias) rows, computed once — every
        alias question is a scan of this instead of an ast.walk."""
        if self._imports is None:
            rows: list = []
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        rows.append(("import", a.name, None,
                                     a.asname or a.name))
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for a in node.names:
                        rows.append(("from", node.module, a.name,
                                     a.asname or a.name))
            self._imports = rows
        return self._imports

    @classmethod
    def parse(cls, abspath: str, relpath: str,
              known_rules: Iterable[str]) -> "FileContext | Finding":
        try:
            with open(abspath, encoding="utf-8", errors="replace") as f:
                source = f.read()
            tree = ast.parse(source, filename=relpath)
        except (SyntaxError, ValueError, OSError) as e:
            return Finding(relpath, getattr(e, "lineno", None) or 1,
                           PARSE_ERROR, f"cannot lint: {e}")
        waivers, problems = _parse_waivers(source, relpath,
                                           set(known_rules))
        return cls(abspath, relpath, source, tree, waivers, problems)

    def waiver_for(self, rule: str, line: int) -> str | None:
        """The waiver reason covering (rule, line), or None."""
        w = self.waivers.get(line)
        if w is None:
            return None
        return w.get(rule)


def _parse_waivers(source: str, relpath: str, known_rules: set[str]
                   ) -> tuple[dict[int, dict[str, str]], list[Finding]]:
    """Extract ``# pblint: disable=...`` comments.

    A trailing comment waives its own line; a standalone comment line
    waives the next line that carries code (so a waiver can sit above a
    long statement without blowing the line length).
    """
    comments: list[tuple[int, bool, str]] = []   # (line, standalone, text)
    code_lines: set[int] = set()
    try:
        toks = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenizeError, IndentationError, SyntaxError):
        return {}, []
    for tok in toks:
        if tok.type == tokenize.COMMENT:
            prefix = tok.line[:tok.start[1]]
            comments.append((tok.start[0], not prefix.strip(),
                             tok.string))
        elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                              tokenize.INDENT, tokenize.DEDENT,
                              tokenize.ENDMARKER):
            for ln in range(tok.start[0], tok.end[0] + 1):
                code_lines.add(ln)

    waivers: dict[int, dict[str, str]] = {}
    problems: list[Finding] = []
    for line, standalone, text in comments:
        m = _WAIVER_RE.search(text)
        if m is None:
            if "pblint:" in text:
                problems.append(Finding(
                    relpath, line, BAD_WAIVER,
                    "unrecognized pblint comment (want `# pblint: "
                    "disable=<rule>[,<rule>] -- <reason>`): "
                    f"{text.strip()[:80]!r}"))
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = m.group("reason") or ""
        bad = False
        if not reason.strip():
            problems.append(Finding(
                relpath, line, BAD_WAIVER,
                f"waiver for {','.join(rules)} has no reason — the reason "
                "is mandatory (`-- <why>`); the waiver is NOT honored"))
            bad = True
        unknown = [r for r in rules if r not in known_rules]
        if unknown:
            problems.append(Finding(
                relpath, line, BAD_WAIVER,
                f"waiver names unknown rule(s) {', '.join(unknown)} "
                f"(known: {', '.join(sorted(known_rules))})"))
            bad = True
        if bad:
            continue
        target = line
        if standalone:
            later = [ln for ln in code_lines if ln > line]
            if not later:
                continue
            target = min(later)
        slot = waivers.setdefault(target, {})
        for r in rules:
            slot[r] = reason.strip()
    return waivers, problems


# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def call_kwarg(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def iter_calls(tree: ast.AST) -> Iterator[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def import_aliases(ctx: "FileContext", module: str, names: Iterable[str]
                   ) -> dict[str, str]:
    """Local alias -> canonical name, for ``from <module> import <name>
    [as alias]`` over the given names."""
    want = set(names)
    out: dict[str, str] = {}
    for kind, mod, name, alias in ctx.import_table:
        if kind == "from" and mod == module and name in want:
            out[alias] = name
    return out


def module_aliases(ctx: "FileContext", module: str) -> set[str]:
    """Dotted prefixes under which ``module`` is reachable in this file:
    handles ``import m``, ``import m as x``, ``from pkg import leaf``."""
    head, _, leaf = module.rpartition(".")
    out: set[str] = set()
    for kind, mod, name, alias in ctx.import_table:
        if kind == "import" and mod == module:
            out.add(alias)
        elif kind == "from" and leaf and mod == head and name == leaf:
            out.add(alias)
    return out


# ---------------------------------------------------------------------------
# flag / faultpoint reference extraction (shared by index + rules)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FlagRef:
    name: str
    line: int
    is_read: bool


def flag_object_prefixes(ctx: FileContext, project: Project) -> set[str]:
    """Dotted names under which this file can reach the flags object."""
    pkg = project.package
    cfg_mod = f"{pkg}.config"
    prefixes: set[str] = set()
    for alias, canon in import_aliases(ctx, cfg_mod,
                                       ("flags",)).items():
        prefixes.add(alias)
    for alias, canon in import_aliases(ctx, pkg, ("flags",)).items():
        prefixes.add(alias)
    for mod_alias in module_aliases(ctx, cfg_mod):
        prefixes.add(f"{mod_alias}.flags")
    for mod_alias in module_aliases(ctx, pkg):
        prefixes.add(f"{mod_alias}.flags")
    return prefixes


_FLAGS_METHODS = ("set", "get", "from_env")


def iter_flag_refs(ctx: FileContext, project: Project
                   ) -> Iterator[FlagRef]:
    """Every reference to a flags-registry field in this file: attribute
    loads/stores on the flags object, literal ``flags.get/set`` names,
    and ``set_flags(name=...)`` keywords."""
    prefixes = flag_object_prefixes(ctx, project)
    set_flags_aliases = set(import_aliases(
        ctx, f"{project.package}.config", ("set_flags",)))
    cfg_mod_aliases = module_aliases(ctx, f"{project.package}.config")
    if not prefixes and not set_flags_aliases and not cfg_mod_aliases:
        return
    method_call_funcs: set[int] = set()
    for call in iter_calls(ctx.tree):
        f = call.func
        # flags.get("x") / flags.set("x", v)
        if (isinstance(f, ast.Attribute) and f.attr in ("get", "set")
                and dotted_name(f.value) in prefixes):
            method_call_funcs.add(id(f))
            lit = str_const(call.args[0]) if call.args else None
            if lit is not None:
                yield FlagRef(lit, call.lineno, f.attr == "get")
        # set_flags(a=..., b=...) — by from-import alias or module attr
        is_set_flags = (isinstance(f, ast.Name)
                        and f.id in set_flags_aliases) or (
            isinstance(f, ast.Attribute) and f.attr == "set_flags"
            and dotted_name(f.value) in cfg_mod_aliases)
        if is_set_flags:
            for kw in call.keywords:
                if kw.arg:
                    yield FlagRef(kw.arg, call.lineno, False)
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Attribute) and id(node) not in
                method_call_funcs and dotted_name(node.value) in prefixes):
            if node.attr in _FLAGS_METHODS or node.attr.startswith("__"):
                continue
            yield FlagRef(node.attr, node.lineno,
                          isinstance(node.ctx, ast.Load))


@dataclasses.dataclass(frozen=True)
class FaultpointRef:
    name: str
    line: int


def iter_faultpoint_refs(ctx: FileContext, project: Project
                         ) -> Iterator[FaultpointRef]:
    """Literal faultpoint names used in this file: ``hit("x")`` /
    ``arm("x")`` (direct or via the module), and ``fault_point="x"``
    keywords on any call (the atomic_file / write_manifest plumbing).
    Non-literal names are skipped — they are forwarding plumbing, and
    their literal sources are checked at the caller."""
    fp_mod = f"{project.package}.utils.faultpoint"
    fn_aliases = import_aliases(ctx, fp_mod, ("hit", "arm"))
    mod_names = module_aliases(ctx, fp_mod)
    for call in iter_calls(ctx.tree):
        f = call.func
        is_hit = (isinstance(f, ast.Name) and f.id in fn_aliases) or (
            isinstance(f, ast.Attribute) and f.attr in ("hit", "arm")
            and dotted_name(f.value) in mod_names)
        if is_hit and call.args:
            lit = str_const(call.args[0])
            if lit is not None:
                yield FaultpointRef(lit, call.lineno)
        kw = call_kwarg(call, "fault_point")
        if kw is not None:
            lit = str_const(kw)
            if lit is not None:
                yield FaultpointRef(lit, call.lineno)


# ---------------------------------------------------------------------------
# cross-file index
# ---------------------------------------------------------------------------

class ProjectIndex:
    """Cross-file facts: built once over lint targets + reference trees."""

    def __init__(self) -> None:
        self.flags_fields: dict[str, int] = {}      # field -> config.py line
        self.flag_reads: dict[str, list[tuple[str, int]]] = {}
        self.faultpoint_registries: dict[str, dict[str, int]] = {}
        self.faultpoint_sites: dict[str, list[tuple[str, int]]] = {}
        self.event_registries: dict[str, dict[str, int]] = {}
        self.test_literals: set[str] = set()
        self.test_registry_refs: set[str] = set()

    @property
    def all_faultpoints(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for reg in self.faultpoint_registries.values():
            out.update(reg)
        return out

    def point_registries(self, point: str) -> list[str]:
        return [name for name, reg in self.faultpoint_registries.items()
                if point in reg]

    def point_is_tested(self, point: str) -> bool:
        """A point is test-referenced when its exact name appears as a
        string literal under tests/, or a test references a registry
        tuple the point is a member of (the kill matrices parametrize
        over the closed registries — that IS per-member coverage)."""
        if point in self.test_literals:
            return True
        return any(r in self.test_registry_refs
                   for r in self.point_registries(point))

    # ---- builders --------------------------------------------------------

    def add_flags_module(self, ctx: FileContext, project: Project) -> None:
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.ClassDef)
                    and node.name == project.flags_class):
                for stmt in node.body:
                    tgt = None
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                            stmt.target, ast.Name):
                        tgt = stmt.target.id
                    elif isinstance(stmt, ast.Assign) and len(
                            stmt.targets) == 1 and isinstance(
                            stmt.targets[0], ast.Name):
                        tgt = stmt.targets[0].id
                    if tgt and not tgt.startswith("_"):
                        self.flags_fields[tgt] = stmt.lineno
                break

    def add_faultpoint_module(self, ctx: FileContext,
                              project: Project) -> None:
        for node in ctx.tree.body if isinstance(
                ctx.tree, ast.Module) else []:
            tgt = None
            value = None
            if isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name):
                tgt, value = node.target.id, node.value
            elif isinstance(node, ast.Assign) and len(
                    node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name):
                tgt, value = node.targets[0].id, node.value
            if tgt in project.faultpoint_registries and isinstance(
                    value, (ast.Tuple, ast.List)):
                reg = self.faultpoint_registries.setdefault(tgt, {})
                for el in value.elts:
                    lit = str_const(el)
                    if lit is not None:
                        reg[lit] = el.lineno

    @property
    def all_event_names(self) -> "set[str]":
        out: set = set()
        for reg in self.event_registries.values():
            out.update(reg)
        return out

    def add_event_registry_module(self, ctx: FileContext,
                                  project: Project) -> None:
        """Collect the closed hub event/span name registry — the same
        module-level-tuple shape as the faultpoint registries."""
        for node in ctx.tree.body if isinstance(
                ctx.tree, ast.Module) else []:
            tgt = None
            value = None
            if isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name):
                tgt, value = node.target.id, node.value
            elif isinstance(node, ast.Assign) and len(
                    node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name):
                tgt, value = node.targets[0].id, node.value
            if tgt in project.event_registries and isinstance(
                    value, (ast.Tuple, ast.List, ast.Set)):
                reg = self.event_registries.setdefault(tgt, {})
                for el in value.elts:
                    lit = str_const(el)
                    if lit is not None:
                        reg[lit] = el.lineno

    def add_reference_file(self, ctx: FileContext, project: Project
                           ) -> None:
        for ref in iter_flag_refs(ctx, project):
            if ref.is_read:
                self.flag_reads.setdefault(ref.name, []).append(
                    (ctx.relpath, ref.line))
        for ref in iter_faultpoint_refs(ctx, project):
            self.faultpoint_sites.setdefault(ref.name, []).append(
                (ctx.relpath, ref.line))

    def add_test_file(self, ctx: FileContext, project: Project) -> None:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(
                    node.value, str):
                self.test_literals.add(node.value)
            elif isinstance(node, ast.Name) and (
                    node.id in project.faultpoint_registries):
                self.test_registry_refs.add(node.id)
            elif isinstance(node, ast.Attribute) and (
                    node.attr in project.faultpoint_registries):
                self.test_registry_refs.add(node.attr)
        # tests reference flags too (set_flags in fixtures): count reads
        self.add_reference_file(ctx, project)


# ---------------------------------------------------------------------------
# rules base + linter
# ---------------------------------------------------------------------------

class Rule:
    """One invariant. ``id`` is the waiver/CLI name; ``doc`` one line."""

    id: str = ""
    doc: str = ""

    def visit_file(self, ctx: FileContext, index: ProjectIndex,
                   project: Project) -> list[Finding]:
        return []

    def check_project(self, index: ProjectIndex, project: Project,
                      contexts: dict[str, FileContext]) -> list[Finding]:
        return []


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]                    # unwaived, unbaselined
    waived: list[tuple[Finding, str]]          # (finding, reason)
    baselined: list[Finding]
    files_linted: int

    @property
    def clean(self) -> bool:
        return not self.findings


def _iter_py_files(path: str) -> Iterator[str]:
    if os.path.isfile(path):
        if path.endswith(".py"):
            yield path
        return
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__" and not d.startswith(".")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


class Linter:
    def __init__(self, project: Project, rules: "list[Rule] | None" = None):
        from paddlebox_tpu.analysis.rules import ALL_RULES
        self.project = project
        self.rules = list(rules) if rules is not None else [
            r() for r in ALL_RULES]
        self.rule_ids = {r.id for r in self.rules}

    def _known_waiver_rules(self) -> set[str]:
        # every shipped rule is waivable by name even when --rules narrows
        # the active set — a narrowed run must not misreport the other
        # rules' waivers as unknown
        from paddlebox_tpu.analysis.rules import ALL_RULES
        return {r.id for r in ALL_RULES} | {BAD_WAIVER, PARSE_ERROR}

    def lint(self, paths: Iterable[str],
             baseline: "set[tuple[str, str, str]] | None" = None
             ) -> LintResult:
        project = self.project
        known = self._known_waiver_rules()

        # 1. parse lint targets. Relative paths resolve against the repo
        # root first (the gate's convention), then the CWD; a path that
        # matches NOTHING is an error — a gate that silently lints zero
        # files would report a false green on a typo'd invocation.
        contexts: dict[str, FileContext] = {}
        hard_findings: list[Finding] = []
        for path in paths:
            if os.path.isabs(path):
                resolved = path
            else:
                resolved = os.path.join(project.root, path)
                if not os.path.exists(resolved) and os.path.exists(path):
                    resolved = os.path.abspath(path)
            matched = False
            for f in _iter_py_files(resolved):
                matched = True
                rel = project.relpath(f)
                if rel in contexts:
                    continue
                got = FileContext.parse(f, rel, known)
                if isinstance(got, Finding):
                    hard_findings.append(got)
                else:
                    contexts[rel] = got
            if not matched:
                raise FileNotFoundError(
                    f"lint path {path!r} matched no .py files (looked at "
                    f"{resolved}) — refusing to report a clean run over "
                    "nothing")

        # 2. parse reference-only trees (tests, bench, examples) and any
        # load-bearing module not among the targets
        index = ProjectIndex()
        ref_contexts: dict[str, FileContext] = {}

        def _ref_ctx(rel: str) -> FileContext | None:
            if rel in contexts:
                return contexts[rel]
            if rel in ref_contexts:
                return ref_contexts[rel]
            ab = os.path.join(project.root, rel)
            if not os.path.isfile(ab):
                return None
            got = FileContext.parse(ab, rel, known)
            if isinstance(got, Finding):
                return None
            ref_contexts[rel] = got
            return got

        fctx = _ref_ctx(project.flags_module)
        if fctx is not None:
            index.add_flags_module(fctx, project)
        pctx = _ref_ctx(project.faultpoint_module)
        if pctx is not None:
            index.add_faultpoint_module(pctx, project)
        ectx = _ref_ctx(project.event_registry_module)
        if ectx is not None:
            index.add_event_registry_module(ectx, project)

        for ctx in contexts.values():
            index.add_reference_file(ctx, project)
        for aux in project.aux_reference_paths:
            ab = os.path.join(project.root, aux)
            if not os.path.exists(ab):
                continue
            for f in _iter_py_files(ab):
                ctx = _ref_ctx(project.relpath(f))
                if ctx is not None and ctx.relpath not in contexts:
                    index.add_reference_file(ctx, project)
        tests_ab = os.path.join(project.root, project.tests_dir)
        if os.path.isdir(tests_ab):
            for f in _iter_py_files(tests_ab):
                ctx = _ref_ctx(project.relpath(f))
                if ctx is not None:
                    index.add_test_file(ctx, project)

        # 3. run rules
        raw: list[Finding] = list(hard_findings)
        for ctx in contexts.values():
            raw.extend(ctx.waiver_problems)
            for rule in self.rules:
                raw.extend(rule.visit_file(ctx, index, project))
        for rule in self.rules:
            for f in rule.check_project(index, project, contexts):
                # project-level findings anchor at a file; only report
                # them when that file is being linted (linting one leaf
                # file must not surface whole-repo findings)
                if f.file in contexts:
                    raw.append(f)

        # 4. waivers + baseline
        findings: list[Finding] = []
        waived: list[tuple[Finding, str]] = []
        baselined: list[Finding] = []
        for f in sorted(set(raw)):
            ctx = contexts.get(f.file)
            reason = ctx.waiver_for(f.rule, f.line) if ctx else None
            if reason is not None and f.rule not in (BAD_WAIVER,
                                                     PARSE_ERROR):
                waived.append((f, reason))
            elif baseline and f.key() in baseline:
                baselined.append(f)
            else:
                findings.append(f)
        return LintResult(findings, waived, baselined, len(contexts))


# ---------------------------------------------------------------------------
# baseline — machine-readable accepted-findings snapshot
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: str) -> set[tuple[str, str, str]]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"baseline {path}: version {doc.get('version')!r} "
                         f"(want {BASELINE_VERSION})")
    return {(e["file"], e["rule"], e["message"])
            for e in doc.get("findings", [])}


def baseline_doc(findings: Iterable[Finding],
                 rule_ids: Iterable[str]) -> dict:
    return {
        "version": BASELINE_VERSION,
        "tool": "pblint",
        "rules": sorted(rule_ids),
        "findings": [
            {"file": f.file, "line": f.line, "rule": f.rule,
             "message": f.message}
            for f in sorted(findings)],
    }


def write_baseline(path: str, findings: Iterable[Finding],
                   rule_ids: Iterable[str]) -> None:
    doc = baseline_doc(findings, rule_ids)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
