"""The pblint rules. Each is grounded in a real prior incident — see
docs/INVARIANTS.md for the incident catalogue and how to add a rule.

A rule is one class: ``id`` (the waiver / --rules name), ``doc`` (one
line for --list-rules), a per-file :meth:`visit_file`, and optionally a
whole-project :meth:`check_project` for facts no single file can
establish. Register new rules in :data:`ALL_RULES`; ship them with a
fixture test in tests/test_pblint.py proving they fire on a violation
and stay quiet on the fixed/waived form, or land them behind a baseline
(``--write-baseline`` / ``--baseline``) when the tree is not yet clean.
"""

from __future__ import annotations

import ast

from paddlebox_tpu.analysis.core import (
    FileContext,
    Finding,
    Project,
    ProjectIndex,
    Rule,
    call_kwarg,
    dotted_name,
    import_aliases,
    iter_calls,
    iter_faultpoint_refs,
    iter_flag_refs,
    module_aliases,
    str_const,
)

# ---------------------------------------------------------------------------
# durable-write
# ---------------------------------------------------------------------------

def _open_write_mode(call: ast.Call) -> str | None:
    """The mode string when this is an ``open(path, "w"/"wb"/...)``."""
    f = call.func
    name = f.id if isinstance(f, ast.Name) else None
    if name != "open":
        return None
    mode_node = call.args[1] if len(call.args) > 1 else call_kwarg(
        call, "mode")
    mode = str_const(mode_node) if mode_node is not None else None
    if mode is not None and ("w" in mode or "x" in mode):
        return mode
    return None


def _atomic_bindings(tree: ast.AST) -> list[tuple[str, int, int]]:
    """(name, first_line, last_line) for every ``with ...atomic_file(...)
    as name`` body — opens of that name inside the body are sanctioned."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            ce = item.context_expr
            if not (isinstance(ce, ast.Call)
                    and isinstance(dotted_name(ce.func), str)
                    and dotted_name(ce.func).split(".")[-1]
                    == "atomic_file"):
                continue
            if isinstance(item.optional_vars, ast.Name):
                out.append((item.optional_vars.id, node.lineno,
                            node.end_lineno or node.lineno))
    return out


def _local_idiom_tmp_names(tree: ast.AST) -> list[tuple[str, int, int]]:
    """(tmp_name, first_line, last_line) per function carrying the
    tmp->fsync->os.replace idiom: only names that are the SOURCE of an
    ``os.replace(tmp, ...)`` in a function that also fsyncs are
    sanctioned — a second raw open to a different final path in the same
    function stays a finding (whole-function sanctioning would pass
    exactly the torn-write class the rule exists to catch)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        has_fsync = False
        replaced: set[str] = set()
        for call in iter_calls(node):
            d = dotted_name(call.func) or ""
            if d.split(".")[-1] == "fsync":
                has_fsync = True
            if d == "os.replace" and call.args and isinstance(
                    call.args[0], ast.Name):
                replaced.add(call.args[0].id)
        if has_fsync and replaced:
            a, b = node.lineno, node.end_lineno or node.lineno
            out.extend((name, a, b) for name in replaced)
    return out


class DurableWriteRule(Rule):
    id = "durable-write"
    doc = ("raw open(..., 'w'/'wb') in a durability module must flow "
           "through atomic_file / fs_lib.put_replacing or the local "
           "tmp->fsync->os.replace idiom")

    def visit_file(self, ctx: FileContext, index: ProjectIndex,
                   project: Project) -> list[Finding]:
        if not project.in_durability_module(ctx.relpath):
            return []
        bindings = _atomic_bindings(ctx.tree)
        idiom_tmps = _local_idiom_tmp_names(ctx.tree)
        out = []
        for call in iter_calls(ctx.tree):
            mode = _open_write_mode(call)
            if mode is None:
                continue
            target = call.args[0] if call.args else None
            if isinstance(target, ast.Name) and any(
                    target.id == n and a <= call.lineno <= b
                    for n, a, b in bindings):
                continue            # the atomic_file tmp handle
            if isinstance(target, ast.Name) and any(
                    target.id == n and a <= call.lineno <= b
                    for n, a, b in idiom_tmps):
                continue            # local tmp->fsync->os.replace idiom
            out.append(Finding(
                ctx.relpath, call.lineno, self.id,
                f"raw open(..., {mode!r}) in a durability module — a "
                "crash mid-write leaves a torn file under the final "
                "name; route it through utils/checkpoint.atomic_file "
                "(or fs_lib.put_replacing for uploads), or write "
                "tmp -> fsync -> os.replace locally (PR-3 incident: "
                "every snapshot writer was converted to this)"))
        return out


# ---------------------------------------------------------------------------
# faultpoint-registry
# ---------------------------------------------------------------------------

class FaultpointRegistryRule(Rule):
    id = "faultpoint-registry"
    doc = ("every faultpoint hit/arm site names a registered point, and "
           "every registered point is referenced by a test under tests/")

    def visit_file(self, ctx: FileContext, index: ProjectIndex,
                   project: Project) -> list[Finding]:
        if ctx.relpath == project.faultpoint_module:
            return []               # the registry/dispatcher itself
        points = index.all_faultpoints
        if not points and not index.faultpoint_registries:
            return []               # no registry in this project: no rule
        out = []
        for ref in iter_faultpoint_refs(ctx, project):
            if ref.name not in points:
                regs = ", ".join(project.faultpoint_registries)
                out.append(Finding(
                    ctx.relpath, ref.line, self.id,
                    f"faultpoint {ref.name!r} is not in the closed "
                    f"registry ({regs}) — register it in "
                    f"{project.faultpoint_module} so the kill->resume "
                    "matrices cover it (an unregistered crash window is "
                    "an untested crash window)"))
        return out

    def check_project(self, index: ProjectIndex, project: Project,
                      contexts: dict[str, FileContext]) -> list[Finding]:
        out = []
        for point, line in sorted(index.all_faultpoints.items()):
            if not index.point_is_tested(point):
                out.append(Finding(
                    project.faultpoint_module, line, self.id,
                    f"faultpoint {point!r} is registered but no test "
                    f"under {project.tests_dir}/ references it (by "
                    "literal name or by parametrizing over its registry "
                    "tuple) — a registered-but-untested kill point "
                    "proves nothing"))
        return out


# ---------------------------------------------------------------------------
# thread-context
# ---------------------------------------------------------------------------

class ThreadContextRule(Rule):
    id = "thread-context"
    doc = ("threading.Thread outside monitor/context.py loses pass/step "
           "telemetry tagging — use monitor.context.spawn")

    def visit_file(self, ctx: FileContext, index: ProjectIndex,
                   project: Project) -> list[Finding]:
        if ctx.relpath == project.thread_context_module:
            return []               # the sanctioned wrapper itself
        mod_names = module_aliases(ctx, "threading")
        fn_aliases = import_aliases(ctx, "threading", ("Thread",))
        out = []
        for call in iter_calls(ctx.tree):
            f = call.func
            is_thread = (isinstance(f, ast.Attribute)
                         and f.attr == "Thread"
                         and dotted_name(f.value) in mod_names) or (
                isinstance(f, ast.Name) and f.id in fn_aliases)
            if is_thread:
                out.append(Finding(
                    ctx.relpath, call.lineno, self.id,
                    "raw threading.Thread starts with an EMPTY "
                    "contextvars context, so telemetry from the worker "
                    "loses its pass/step tags (PR-4 incident: pack/"
                    "stager/dump threads emitted untagged events) — "
                    "spawn through monitor.context.spawn, or waive with "
                    "the reason the thread must not inherit context"))
        return out


# ---------------------------------------------------------------------------
# donefile-discipline
# ---------------------------------------------------------------------------

def _walk_values(node: ast.AST):
    """ast.walk, but skipping every Call's ``func`` subtree: a method
    NAMED after donefiles (``_read_donefile_raw()``) reads one, it does
    not make its result a donefile *path* — only literals, names, and
    value attributes carry path taint."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(n, ast.Call) and child is n.func:
                continue
            stack.append(child)


def _mentions_donefile(node: ast.AST, tainted: "set[str] | None" = None
                       ) -> bool:
    for sub in _walk_values(node):
        lit = str_const(sub)
        if lit is not None and "donefile" in lit.lower():
            return True
        if isinstance(sub, ast.Name) and (
                "donefile" in sub.id.lower()
                or (tainted and sub.id in tainted)):
            return True
        if isinstance(sub, ast.Attribute) and (
                "donefile" in sub.attr.lower()):
            return True
    return False


def _donefile_ish_names(tree: ast.AST) -> set[str]:
    """Names (module- or function-local) assigned from expressions that
    mention a donefile — two propagation passes so ``alt = f"{path}.x"``
    chains resolve."""
    names: set[str] = set()
    for _ in range(2):
        for node in ast.walk(tree):
            tgt = None
            if isinstance(node, ast.Assign) and len(
                    node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name):
                tgt, val = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name) and node.value is not None:
                tgt, val = node.target.id, node.value
            if tgt and _mentions_donefile(val, names):
                names.add(tgt)
    return names


class DonefileDisciplineRule(Rule):
    id = "donefile-discipline"
    doc = ("only fleet/fleet_util.py (and its append_donefile API) may "
           "write a *donefile* target — the one announce channel")

    # (call shape) -> index of the TARGET argument
    _ATTR_TARGETS = {"write_text": 0, "put": 1}
    _DOTTED_TARGETS = {"os.replace": 1, "os.rename": 1,
                       "shutil.copy": 1, "shutil.copy2": 1,
                       "shutil.copyfile": 1, "shutil.move": 1}

    def visit_file(self, ctx: FileContext, index: ProjectIndex,
                   project: Project) -> list[Finding]:
        if ctx.relpath in project.donefile_writers:
            return []
        tainted = _donefile_ish_names(ctx.tree)

        def is_donefile_target(node: ast.AST) -> bool:
            return _mentions_donefile(node, tainted)

        out = []
        for call in iter_calls(ctx.tree):
            f = call.func
            target: ast.AST | None = None
            # open(path, "w"/"a"/...)
            if isinstance(f, ast.Name) and f.id == "open" and call.args:
                mode_node = call.args[1] if len(
                    call.args) > 1 else call_kwarg(call, "mode")
                mode = (str_const(mode_node) or "r"
                        ) if mode_node is not None else "r"
                if "w" in mode or "a" in mode or "x" in mode or (
                        "+" in mode):
                    target = call.args[0]
            elif isinstance(f, ast.Attribute):
                if f.attr == project.donefile_appender:
                    continue        # the sanctioned API
                d = dotted_name(f)
                if d in self._DOTTED_TARGETS:
                    i = self._DOTTED_TARGETS[d]
                    target = call.args[i] if len(call.args) > i else None
                elif f.attr in self._ATTR_TARGETS:
                    i = self._ATTR_TARGETS[f.attr]
                    target = call.args[i] if len(call.args) > i else None
                elif f.attr == "put_replacing":
                    target = call.args[2] if len(call.args) > 2 else None
            elif isinstance(f, ast.Name) and f.id == "put_replacing":
                target = call.args[2] if len(call.args) > 2 else None
            if target is not None and is_donefile_target(target):
                writers = ", ".join(project.donefile_writers)
                out.append(Finding(
                    ctx.relpath, call.lineno, self.id,
                    "write to a *donefile* target outside the "
                    f"sanctioned writer ({writers}) — donefile lines "
                    "are the ONLY model-visibility channel and must "
                    f"ride FleetUtil.{project.donefile_appender} "
                    "(append-after-commit, crash-replay dedup; PR-7 "
                    "made this 'donefile discipline in ONE place')"))
        return out


# ---------------------------------------------------------------------------
# flag-audit
# ---------------------------------------------------------------------------

class FlagAuditRule(Rule):
    id = "flag-audit"
    doc = ("every flags.X read resolves to a config.py field, and every "
           "field is read somewhere — no phantom or dead flags")

    def visit_file(self, ctx: FileContext, index: ProjectIndex,
                   project: Project) -> list[Finding]:
        if not index.flags_fields:
            return []
        out = []
        for ref in iter_flag_refs(ctx, project):
            if ref.name not in index.flags_fields:
                out.append(Finding(
                    ctx.relpath, ref.line, self.id,
                    f"flags.{ref.name} does not resolve to a field of "
                    f"{project.flags_class} in {project.flags_module} — "
                    "a phantom flag reads as a typo'd knob that "
                    "silently never engages (the registry is closed, "
                    "like the reference's flags.cc)"))
        return out

    def check_project(self, index: ProjectIndex, project: Project,
                      contexts: dict[str, FileContext]) -> list[Finding]:
        out = []
        for field, line in sorted(index.flags_fields.items()):
            if not index.flag_reads.get(field):
                out.append(Finding(
                    project.flags_module, line, self.id,
                    f"flag {field!r} is never read anywhere (package, "
                    "tests, bench, examples) — a dead flag documents "
                    "behavior the code does not have; remove it, wire "
                    "it, or waive naming the future consumer"))
        return out


# ---------------------------------------------------------------------------
# event-registry
# ---------------------------------------------------------------------------

class EventRegistryRule(Rule):
    id = "event-registry"
    doc = ("every hub event/span name emitted in the tree must be in the "
           "closed registry (monitor/names.py) — no forked telemetry "
           "namespace")

    def visit_file(self, ctx: FileContext, index: ProjectIndex,
                   project: Project) -> list[Finding]:
        if ctx.relpath == project.event_registry_module:
            return []               # the registry itself
        names = index.all_event_names
        if not names:
            return []               # no registry in this project: no rule
        fn_aliases = (
            import_aliases(ctx, "paddlebox_tpu.monitor",
                           ("event", "span"))
            | import_aliases(ctx, "paddlebox_tpu.monitor.hub",
                             ("event", "span")))
        out = []
        for call in iter_calls(ctx.tree):
            f = call.func
            is_emit = (isinstance(f, ast.Attribute)
                       and f.attr in ("event", "span")) or (
                isinstance(f, ast.Name) and f.id in fn_aliases)
            if not is_emit:
                continue
            arg = call.args[0] if call.args else call_kwarg(call, "name")
            lit = str_const(arg) if arg is not None else None
            if lit is None:
                out.append(Finding(
                    ctx.relpath, call.lineno, self.id,
                    "event/span name is not a string literal — the "
                    "registry check cannot see it (dashboards, doctor "
                    "rules, and the world-trace merger key off names "
                    "verbatim); emit a literal registered in "
                    f"{project.event_registry_module}, or waive naming "
                    "the registered names the expression takes"))
            elif lit not in names:
                regs = ", ".join(project.event_registries)
                out.append(Finding(
                    ctx.relpath, call.lineno, self.id,
                    f"event/span name {lit!r} is not in the closed "
                    f"registry ({regs} in "
                    f"{project.event_registry_module}) — an unregistered "
                    "name silently forks the telemetry namespace every "
                    "consumer greps (register it next to the consumer "
                    "that reads it)"))
        return out


# ---------------------------------------------------------------------------
# silent-except
# ---------------------------------------------------------------------------

class SilentExceptRule(Rule):
    id = "silent-except"
    doc = ("`except ...: pass` without a telemetry event swallows "
           "errors invisibly — count/log it, or waive with the reason "
           "silence is correct")

    def visit_file(self, ctx: FileContext, index: ProjectIndex,
                   project: Project) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            body = [s for s in node.body
                    if not (isinstance(s, ast.Expr)
                            and str_const(s.value) is not None)]
            if len(body) == 1 and isinstance(body[0], ast.Pass):
                out.append(Finding(
                    ctx.relpath, node.lineno, self.id,
                    "silent `except: pass` — the swallowed error leaves "
                    "no counter, no event, no trace (the PR-7 "
                    "malformed-donefile incident: a torn line was "
                    "re-swallowed every poll); emit a telemetry "
                    "counter/event, or waive stating why silence is "
                    "the correct behavior here"))
        return out


ALL_RULES: tuple[type[Rule], ...] = (
    DurableWriteRule,
    FaultpointRegistryRule,
    ThreadContextRule,
    DonefileDisciplineRule,
    FlagAuditRule,
    EventRegistryRule,
    SilentExceptRule,
)
