"""pblint CLI.

Usage::

    python -m paddlebox_tpu.analysis.lint [paths...] [options]

Paths default to the package directory. Each finding prints as one
``file:line rule message`` line on stdout; exit code 0 = clean,
1 = unwaived findings, 2 = usage error.

Options:

``--rules r1,r2``      run only these rules (waivers for the others
                       still parse — a narrowed run never misreports
                       them as unknown)
``--list-rules``       print ``id  doc`` per rule and exit
``--json``             machine-readable report on stdout
``--baseline FILE``    findings recorded in FILE are accepted (reported
                       in the summary, excluded from the exit code) —
                       the incremental-adoption path for new rules
``--write-baseline FILE``  record the current unwaived findings and exit
                       0 — then land the new rule, and burn the baseline
                       down over subsequent PRs
``--show-waived``      also print waived findings with their reasons
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from paddlebox_tpu.analysis.core import (
    Linter,
    Project,
    load_baseline,
    write_baseline,
)
from paddlebox_tpu.analysis.rules import ALL_RULES


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m paddlebox_tpu.analysis.lint",
        description="pblint: AST-based project-invariant linter")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the "
                        "paddlebox_tpu package)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--baseline", default=None, metavar="FILE")
    p.add_argument("--write-baseline", default=None, metavar="FILE")
    p.add_argument("--show-waived", action="store_true")
    p.add_argument("--root", default=None,
                   help="repo root (default: discovered by walking up "
                        "from the first path)")
    return p


def main(argv: "list[str] | None" = None) -> int:
    args = build_arg_parser().parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.id:22s} {cls.doc}")
        return 0

    paths = args.paths
    if not paths:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))]
    project = (Project(root=os.path.abspath(args.root)) if args.root
               else Project.discover(paths[0]))

    rules = None
    if args.rules:
        want = {r.strip() for r in args.rules.split(",") if r.strip()}
        known = {cls.id for cls in ALL_RULES}
        bad = want - known
        if bad:
            print(f"unknown rule(s): {', '.join(sorted(bad))} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2
        rules = [cls() for cls in ALL_RULES if cls.id in want]

    baseline = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"cannot load baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2

    linter = Linter(project, rules)
    try:
        result = linter.lint(paths, baseline=baseline)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(args.write_baseline, result.findings,
                       [r.id for r in linter.rules])
        print(f"wrote baseline with {len(result.findings)} finding(s) "
              f"to {args.write_baseline}")
        return 0

    if args.as_json:
        json.dump({
            "files_linted": result.files_linted,
            "findings": [
                {"file": f.file, "line": f.line, "rule": f.rule,
                 "message": f.message} for f in result.findings],
            "waived": [
                {"file": f.file, "line": f.line, "rule": f.rule,
                 "reason": reason} for f, reason in result.waived],
            "baselined": [
                {"file": f.file, "line": f.line, "rule": f.rule}
                for f in result.baselined],
            "clean": result.clean,
        }, sys.stdout, indent=1)
        print()
        return 0 if result.clean else 1

    for f in result.findings:
        print(f.render())
    if args.show_waived:
        for f, reason in result.waived:
            print(f"{f.file}:{f.line} {f.rule} [waived: {reason}]")
    print(f"pblint: {len(result.findings)} finding(s), "
          f"{len(result.waived)} waived, {len(result.baselined)} "
          f"baselined across {result.files_linted} file(s)")
    return 0 if result.clean else 1


if __name__ == "__main__":
    raise SystemExit(main())
