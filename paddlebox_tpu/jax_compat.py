"""Compatibility shims for older JAX releases (0.4.x).

The framework is written against the current JAX surface (``jax.shard_map``,
``jax.typeof``, ``lax.axis_size``, varying-manual-axes metadata on
``ShapeDtypeStruct``). Some deployment images pin jax 0.4.x, where those
names live elsewhere or do not exist; importing this module (done once from
``paddlebox_tpu/__init__``) installs equivalent aliases so the SAME package
code runs on both:

- ``jax.shard_map``      -> ``jax.experimental.shard_map.shard_map``
  (kwarg-compatible for the subset used here: f, mesh, in_specs, out_specs).
- ``jax.typeof``         -> ``jax.core.get_aval`` (callers only getattr
  ``.vma`` with a default, so a plain aval suffices).
- ``lax.axis_size``      -> ``jax.core.axis_frame`` (which on 0.4.x returns
  the static mapped-axis size directly).
- ``shape_struct(...)``  -> ``jax.ShapeDtypeStruct`` accepting a ``vma``
  kwarg on every version (dropped where unsupported) — Pallas ``out_shape``
  builders call this instead of the class.

No behavior changes on a current JAX: every shim is installed only when the
canonical name is missing, and ``shape_struct`` forwards ``vma`` verbatim
when the class accepts it.
"""

from __future__ import annotations

import jax

# True when this process runs a pre-vma JAX (0.4.x shard_map). Besides the
# missing names, ONE semantic differs: differentiating wrt a REPLICATED
# (in_spec P()) argument INSIDE a shard_map body yields the device-local
# cotangent — the vma-typed autodiff of current JAX inserts the psum that
# keeps replicated values replication-invariant; 0.4.x does not. Code that
# relies on the psummed convention (Trainer._mean_replicated_grad) checks
# this flag and inserts the psum explicitly, so dense grads stay the
# global mean on both versions (pinned by the mesh-8 golden trajectory).
LEGACY_SHARD_MAP = not hasattr(jax, "shard_map")

_SDS_HAS_VMA: bool | None = None


def shape_struct(shape, dtype, vma=None):
    """jax.ShapeDtypeStruct with the vma kwarg dropped on old JAX."""
    global _SDS_HAS_VMA
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    if _SDS_HAS_VMA is None:
        try:
            jax.ShapeDtypeStruct((), jax.numpy.float32, vma=frozenset())
            _SDS_HAS_VMA = True
        except TypeError:
            _SDS_HAS_VMA = False
    if _SDS_HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _install() -> None:
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
            kwargs.pop("check_vma", None)  # new-API spelling of check_rep
            # 0.4.x's static replication checker predates the vma system
            # this code is written against and rejects valid programs
            # (e.g. psummed cotangents of replicated inputs); the modern
            # checker validates these, so disable the old one.
            kwargs.setdefault("check_rep", False)
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax, "typeof"):
        from jax.core import get_aval

        jax.typeof = get_aval

    from jax import lax

    if not hasattr(lax, "axis_size"):
        from jax.core import axis_frame

        def axis_size(axis_name):
            return axis_frame(axis_name)

        lax.axis_size = axis_size

    if not hasattr(lax, "pcast"):
        # pcast only adjusts the varying-manual-axes TYPE of a value; on
        # a pre-vma jax there is no such type (and check_rep is off), so
        # the data-identity is the faithful lowering
        def pcast(x, axis_name, *, to=None):
            del axis_name, to
            return x

        lax.pcast = pcast


_install()
