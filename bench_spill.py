"""Spill-tier scale evidence (VERDICT r4 next #5): a 50M-key table
through SpillEmbeddingStore with the RAM row cache capped far below the
key count — the reference's SSD tier affordability story (LoadSSD2Mem,
box_wrapper.h:487-494: 10^10-key tables are disk-bounded, not
DRAM-bounded) at a scale the unit tests don't touch.

Host-only (tunnel-immune). Writes ONE JSON line (and SPILL_r05.json when
--out is passed):
  - build: 50M fresh keys through lookup_or_init (init + row-file write)
  - two working-set passes with churn (pass B re-fetches 80% of pass A's
    keys + 20% fresh), measuring fetch keys/s and spill-file MB/s
  - memory: the HARD resident floor (key index + row cache + metadata)
    vs the row file size, plus measured RSS before/after dropping the
    file's page cache (clean memmap pages are reclaimable OS cache, not
    working memory — the drop shows the floor is real)

``--policy`` selects the RAM-tier admission policy: ``freq`` (the
show-count-weighted tier manager, embedding/tiering.py — the default)
or ``direct`` (the legacy direct-mapped last-wins install, kept as the
measured baseline the gate-held ``spill_10x`` bench point compares
against). ``--assoc N`` sets the cache's set associativity (default:
``flags.spill_cache_assoc``; ``direct`` forces 1-way — it IS the
direct-mapped geometry). Per-pass hit rates, the admission/eviction
counters, and the per-policy conflict-miss counts are recorded either
way; a final section refreshes a host-planed TrainerReplicaCache off
the tier ranking and replays the last pass's keys against it, so one
run carries the replica-hit numbers next to the RAM-tier ones.

Usage: python bench_spill.py [--keys 50000000] [--policy freq|direct]
                             [--assoc 4] [--out SPILL_r05.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddlebox_tpu.embedding import EmbeddingConfig
from paddlebox_tpu.embedding.spill_store import SpillEmbeddingStore


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024.0
    return float("nan")


def drop_file_cache(store) -> bool:
    """Flush dirty memmap pages, then evict the mapping's resident pages
    (madvise MADV_DONTNEED — fadvise cannot evict pages a live mapping
    references) so RSS shows the HARD resident floor (index + cache),
    not reclaimable file-backed cache.

    Returns whether the madvise eviction succeeded — a failed eviction
    leaves the file's pages resident and would silently report an
    INFLATED "hard floor" RSS as if the drop worked, so callers record
    the outcome next to every RSS-after-drop number."""
    import ctypes
    import errno
    import mmap as mmap_mod
    store._rows.flush()
    mm = store._rows
    libc = ctypes.CDLL(None, use_errno=True)
    addr = mm.ctypes.data
    page = os.sysconf("SC_PAGESIZE")
    base = addr - (addr % page)
    length = mm.nbytes + (addr - base)
    rc = libc.madvise(ctypes.c_void_p(base), ctypes.c_size_t(length),
                      mmap_mod.MADV_DONTNEED)
    ok = rc == 0
    if not ok:
        err = ctypes.get_errno()
        print(f"# madvise(MADV_DONTNEED) failed: "
              f"{errno.errorcode.get(err, err)}", file=sys.stderr,
              flush=True)
    fd = os.open(store._rows_path, os.O_RDONLY)
    try:
        os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
    finally:
        os.close(fd)
    return ok


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=50_000_000)
    ap.add_argument("--pass-keys", type=int, default=4_000_000)
    ap.add_argument("--cache-rows", type=int, default=1 << 21)  # ~109MB
    ap.add_argument("--policy", choices=("freq", "direct"), default="freq")
    ap.add_argument("--assoc", type=int, default=None,
                    help="cache set associativity (default: "
                         "flags.spill_cache_assoc; direct forces 1)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    cfg = EmbeddingConfig(dim=8, optimizer="adagrad", learning_rate=0.05)
    store = SpillEmbeddingStore(cfg, cache_rows=args.cache_rows,
                                initial_capacity=args.keys + 1024,
                                tier_policy=args.policy,
                                cache_assoc=args.assoc)
    rng = np.random.default_rng(0)
    out = {
        "metric": "spill_store_50m_key_scale",
        "total_keys": args.keys,
        "row_width": cfg.row_width,
        "tier_policy": args.policy,
        "spill_cache_assoc": int(store._assoc),
        "ram_cache_rows": args.cache_rows,
        "ram_cache_mb": round(args.cache_rows * cfg.row_width * 4 / 1e6,
                              1),
        "rss_start_mb": round(rss_mb(), 1),
    }

    # --- build: all keys exist on the spill tier ----------------------
    chunk = 2_000_000
    t0 = time.perf_counter()
    for lo in range(0, args.keys, chunk):
        n = min(chunk, args.keys - lo)
        # disjoint strided windows: every key unique without a 50M-key
        # np.unique pass
        keys = (np.arange(lo, lo + n, dtype=np.uint64) * np.uint64(2654435761)
                + np.uint64(1)) | np.uint64(1) << np.uint64(50)
        store.lookup_or_init(keys)
    build_s = time.perf_counter() - t0
    out["build_seconds"] = round(build_s, 1)
    out["build_keys_per_s"] = round(args.keys / build_s)
    out["row_file_gb"] = round(store.spill_file_bytes / 1e9, 3)
    out["rss_after_build_mb"] = round(rss_mb(), 1)

    # --- two passes with churn ----------------------------------------
    def key_window(idx_arr):
        return (idx_arr.astype(np.uint64) * np.uint64(2654435761)
                + np.uint64(1)) | np.uint64(1) << np.uint64(50)

    pa = rng.choice(args.keys, args.pass_keys, replace=False)
    passes = []
    for p, sel in enumerate((pa, None)):
        if sel is None:   # pass B: 80% of pass A + 20% fresh rows
            keep = pa[rng.random(args.pass_keys) < 0.8]
            fresh = rng.choice(args.keys, args.pass_keys - len(keep),
                               replace=False)
            sel = np.concatenate([keep, fresh])
        keys = key_window(np.unique(sel))
        drop_ok = drop_file_cache(store)    # cold spill tier per pass
        h0, m0 = store.cache_hits, store.cache_misses
        t0 = time.perf_counter()
        rows = store.lookup_or_init(keys)
        fetch_s = time.perf_counter() - t0
        # train-like write-back of every fetched row
        rows[:, 0] += 1.0
        t1 = time.perf_counter()
        store.write_back(keys, rows)
        wb_s = time.perf_counter() - t1
        # the pass-boundary re-evaluation the training loop would run
        # (decay + cold-slot demotion + counter flush)
        tier_stats = store.tier_end_pass()
        mb = rows.nbytes / 1e6
        hits = int(store.cache_hits - h0)
        misses = int(store.cache_misses - m0)
        passes.append({
            "keys": int(len(keys)),
            "fetch_seconds": round(fetch_s, 2),
            "fetch_keys_per_s": round(len(keys) / fetch_s),
            "fetch_mb_per_s": round(mb / fetch_s, 1),
            "writeback_mb_per_s": round(mb / wb_s, 1),
            "cache_hits": hits,
            "cache_misses": misses,
            "hit_rate": round(hits / max(1, hits + misses), 4),
            "conflict_misses": int(tier_stats["pass_conflicts"]),
            "tier_admitted": int(tier_stats["admitted"]),
            "tier_evicted": int(tier_stats["evicted"]),
            "tier_hot_rows": int(tier_stats["hot_rows"]),
            "pre_pass_cache_drop_ok": bool(drop_ok),
        })
        last_keys = keys
    out["passes"] = passes
    out["conflict_misses_total"] = int(store.conflict_misses)

    # --- HBM replica tier replay (flags.use_replica_cache path) -------
    # refresh harvests the tier ranking the two passes just built, then
    # the last pass's keys replay against the replica — the fraction the
    # staging would have short-circuited past RAM/SSD entirely
    from paddlebox_tpu.embedding.replica_cache import TrainerReplicaCache
    replica = TrainerReplicaCache(store, mesh=None)
    t0 = time.perf_counter()
    replica_rows = replica.refresh()
    served = replica.serve(np.sort(last_keys))
    out["replica"] = {
        "rows": int(replica_rows),
        "capacity_rows": int(replica.capacity_rows),
        "replica_hits": int(served.n if served is not None else 0),
        "replay_keys": int(len(last_keys)),
        "refresh_and_replay_seconds": round(time.perf_counter() - t0, 3),
    }
    out["rss_after_passes_mb"] = round(rss_mb(), 1)
    out["final_cache_drop_ok"] = bool(drop_file_cache(store))
    out["rss_after_cache_drop_mb"] = round(rss_mb(), 1)
    out["hard_floor_note"] = (
        "resident floor = key index (~16B/key) + RAM row cache + numpy "
        "bookkeeping; the row file's pages are reclaimable OS cache "
        "(rss_after_cache_drop shows the floor), so table capacity is "
        "bounded by DISK, matching the reference's SSD tier")
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
